"""Model zoo: the BASELINE.md config ladder lives here (LeNet/ResNet in
paddle_tpu.vision.models; Llama + MoE families here)."""

from . import llama  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
