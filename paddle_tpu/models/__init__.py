"""Model zoo: the BASELINE.md config ladder lives here (LeNet/ResNet in
paddle_tpu.vision.models; Llama, DiT and MoE families here)."""

from . import dit  # noqa: F401
from . import llama  # noqa: F401
from . import moe_llama  # noqa: F401
from .dit import DiTConfig  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from .moe_llama import MoEConfig  # noqa: F401
