"""Diffusion Transformer (DiT) — BASELINE config #4 (SD3/DiT class).

Reference surface: the reference covers this class of model via its vision +
transformer layers (python/paddle/nn/layer/transformer.py, vision/) and the
fused attention ops; SD3/DiT recipes live downstream (PaddleMIX) on the same
framework primitives.  This module provides the in-framework flagship for the
"mixed conv+attention, bf16" rung of the config ladder.

TPU-first design mirrors models/llama.py: a pure functional core (stacked
layer weights → one lax.scan block), Megatron-style PartitionSpecs over the
("dp","sharding","mp") mesh axes, Pallas flash attention, bf16 params with
fp32 master weights in AdamW, and a rectified-flow/eps-prediction training
step compiled as a single pjit program.

Architecture (DiT-XL/2 style): patchify conv → tokens; timestep sinusoidal
embedding + label embedding → conditioning vector c; N blocks of
adaLN-Zero(attention, mlp) modulated by c; final adaLN + linear → unpatchify.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas import flash_attention as fa


@dataclasses.dataclass
class DiTConfig:
    image_size: int = 32          # latent spatial size (SD3 latents: 32x32)
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    depth: int = 28
    num_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    class_dropout_prob: float = 0.1
    learn_sigma: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def num_patches(self):
        return (self.image_size // self.patch_size) ** 2

    @property
    def out_channels(self):
        return self.in_channels * (2 if self.learn_sigma else 1)

    @staticmethod
    def dit_xl_2():
        return DiTConfig(hidden_size=1152, depth=28, num_heads=16)

    @staticmethod
    def tiny(image=8, patch=2, channels=4, hidden=64, depth=2, heads=4, classes=10):
        return DiTConfig(image_size=image, patch_size=patch, in_channels=channels,
                         hidden_size=hidden, depth=depth, num_heads=heads,
                         num_classes=classes)


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal timestep embedding; t: [b] float in [0, 1000)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def init_params(cfg: DiTConfig, key=None) -> dict:
    key = key if key is not None else jax.random.key(0)
    k = iter(jax.random.split(key, 24))
    h, d = cfg.hidden_size, cfg.depth
    p, c = cfg.patch_size, cfg.in_channels
    mlp = int(h * cfg.mlp_ratio)
    std = 0.02

    def init(kk, shape, scale=std):
        return (jax.random.normal(kk, shape, jnp.float32) * scale).astype(cfg.dtype)

    return {
        "patch_w": init(next(k), (p * p * c, h)),     # patchify projection
        "patch_b": jnp.zeros((h,), cfg.dtype),
        "pos_embed": init(next(k), (cfg.num_patches, h)),
        "t_mlp1": init(next(k), (256, h)),
        "t_mlp1_b": jnp.zeros((h,), cfg.dtype),
        "t_mlp2": init(next(k), (h, h)),
        "t_mlp2_b": jnp.zeros((h,), cfg.dtype),
        # +1 class for the classifier-free-guidance null token
        "label_embed": init(next(k), (cfg.num_classes + 1, h)),
        "blocks": {
            # adaLN-zero: 6 modulation params per block from c (zero-init out)
            "mod_w": jnp.zeros((d, h, 6 * h), cfg.dtype),
            "mod_b": jnp.zeros((d, 6 * h), cfg.dtype),
            "wqkv": init(next(k), (d, h, 3 * h)),
            "wo": init(next(k), (d, h, h)),
            "mlp1": init(next(k), (d, h, mlp)),
            "mlp1_b": jnp.zeros((d, mlp), cfg.dtype),
            "mlp2": init(next(k), (d, mlp, h)),
            "mlp2_b": jnp.zeros((d, h), cfg.dtype),
        },
        "final_mod_w": jnp.zeros((h, 2 * h), cfg.dtype),
        "final_mod_b": jnp.zeros((2 * h,), cfg.dtype),
        "final_w": jnp.zeros((h, p * p * cfg.out_channels), cfg.dtype),
        "final_b": jnp.zeros((p * p * cfg.out_channels,), cfg.dtype),
    }


def param_specs(cfg: DiTConfig) -> dict:
    return {
        "patch_w": P(None, "mp"),
        "patch_b": P(None),
        "pos_embed": P(None, None),
        "t_mlp1": P(None, "mp"),
        "t_mlp1_b": P(None),
        "t_mlp2": P("sharding", "mp"),
        "t_mlp2_b": P(None),
        # num_classes+1 rows (CFG null token) is usually odd — don't shard dim 0
        "label_embed": P(None, "mp"),
        "blocks": {
            "mod_w": P(None, "sharding", "mp"),
            "mod_b": P(None, "mp"),
            "wqkv": P(None, "sharding", "mp"),   # column parallel
            "wo": P(None, "mp", "sharding"),     # row parallel
            "mlp1": P(None, "sharding", "mp"),
            "mlp1_b": P(None, "mp"),
            "mlp2": P(None, "mp", "sharding"),
            "mlp2_b": P(None),
        },
        "final_mod_w": P("sharding", "mp"),
        "final_mod_b": P(None),
        "final_w": P("mp", None),
        "final_b": P(None),
    }


def _modulate(x, shift, scale):
    return x * (1 + scale[:, None, :]) + shift[:, None, :]


def _layer_norm(x, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype)


def _block_forward(cfg: DiTConfig, x, c, bp):
    """One DiT block with adaLN-Zero; x: [b, n, h], c: [b, h]."""
    b, n, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    mod = jax.nn.silu(c) @ bp["mod_w"] + bp["mod_b"]
    (shift_a, scale_a, gate_a, shift_m, scale_m, gate_m) = jnp.split(mod, 6, axis=-1)

    xn = _modulate(_layer_norm(x), shift_a, scale_a)
    qkv = (xn @ bp["wqkv"]).reshape(b, n, 3, nh, hd)
    q, kk, vv = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = fa.flash_attention_bshd(q, kk, vv, causal=False)
    x = x + gate_a[:, None, :] * (attn.reshape(b, n, nh * hd) @ bp["wo"])

    xn = _modulate(_layer_norm(x), shift_m, scale_m)
    hmid = jax.nn.gelu((xn @ bp["mlp1"]) + bp["mlp1_b"], approximate=True)
    x = x + gate_m[:, None, :] * ((hmid @ bp["mlp2"]) + bp["mlp2_b"])
    return x


def forward(cfg: DiTConfig, params, x, t, y, remat=True):
    """Predicted noise for latents x: [b, c, H, W], timesteps t: [b],
    labels y: [b] int (num_classes == null/uncond token)."""
    b, c, H, W = x.shape
    p = cfg.patch_size
    hgrid, wgrid = H // p, W // p

    # patchify: [b, c, H, W] -> [b, n, p*p*c]
    xp = x.reshape(b, c, hgrid, p, wgrid, p)
    xp = xp.transpose(0, 2, 4, 3, 5, 1).reshape(b, hgrid * wgrid, p * p * c)
    tok = (xp.astype(cfg.dtype) @ params["patch_w"]) + params["patch_b"]
    tok = tok + params["pos_embed"][None]

    temb = timestep_embedding(t, 256).astype(cfg.dtype)
    cvec = jax.nn.silu((temb @ params["t_mlp1"]) + params["t_mlp1_b"])
    cvec = (cvec @ params["t_mlp2"]) + params["t_mlp2_b"]
    cvec = cvec + jnp.take(params["label_embed"], y, axis=0)

    def body(carry, bp):
        return _block_forward(cfg, carry, cvec, bp), None

    scan_body = jax.checkpoint(body) if remat else body
    tok, _ = jax.lax.scan(scan_body, tok, params["blocks"])

    mod = jax.nn.silu(cvec) @ params["final_mod_w"] + params["final_mod_b"]
    shift, scale = jnp.split(mod, 2, axis=-1)
    tok = _modulate(_layer_norm(tok), shift, scale)
    out = (tok @ params["final_w"]) + params["final_b"]

    # unpatchify: [b, n, p*p*oc] -> [b, oc, H, W]
    oc = cfg.out_channels
    out = out.reshape(b, hgrid, wgrid, p, p, oc)
    out = out.transpose(0, 5, 1, 3, 2, 4).reshape(b, oc, H, W)
    return out


def loss_fn(cfg: DiTConfig, params, x0, y, rng):
    """Rectified-flow matching loss (SD3-style): x_t = (1-t) x0 + t eps,
    target velocity v = eps - x0."""
    b = x0.shape[0]
    k1, k2, k3 = jax.random.split(rng, 3)
    t = jax.random.uniform(k1, (b,), jnp.float32)
    eps = jax.random.normal(k2, x0.shape, jnp.float32)
    # classifier-free guidance dropout: replace label with null token
    drop = jax.random.bernoulli(k3, cfg.class_dropout_prob, (b,))
    y = jnp.where(drop, cfg.num_classes, y)
    xt = (1 - t[:, None, None, None]) * x0 + t[:, None, None, None] * eps
    v_pred = forward(cfg, params, xt.astype(cfg.dtype), t * 999.0, y)
    v_tgt = eps - x0
    return jnp.mean((v_pred.astype(jnp.float32) - v_tgt) ** 2)


def make_mesh(dp=1, mp=1, sharding=1, sep=1, pp=1, devices=None):
    from . import llama

    return llama.make_mesh(dp=dp, mp=mp, sharding=sharding, sep=sep, pp=pp,
                           devices=devices)


def build_train_step(cfg: DiTConfig, mesh: Mesh, lr=1e-4, weight_decay=0.0,
                     beta1=0.9, beta2=0.999, grad_clip=1.0):
    specs = param_specs(cfg)
    data_spec = P(("dp", "sharding"), None, None, None)  # [b, c, H, W]

    def to_named(tree_specs):
        return jax.tree_util.tree_map(
            lambda sp: NamedSharding(mesh, sp), tree_specs,
            is_leaf=lambda sp: isinstance(sp, P))

    param_shardings = to_named(specs)

    def opt_init(params):
        z = lambda pp_: jnp.zeros(pp_.shape, jnp.float32)
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": jax.tree_util.tree_map(z, params),
            "v": jax.tree_util.tree_map(z, params),
            "master": jax.tree_util.tree_map(lambda pp_: pp_.astype(jnp.float32), params),
        }

    def train_step(params, opt_state, x0, y, rng):
        loss, grads = jax.value_and_grad(
            lambda prm: loss_fn(cfg, prm, x0, y, rng))(params)
        g32 = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        leaves = jax.tree_util.tree_leaves(g32)
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
        scale_f = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-6))
        step = opt_state["step"] + 1
        b1c = 1 - beta1 ** step.astype(jnp.float32)
        b2c = 1 - beta2 ** step.astype(jnp.float32)

        def upd(g, m, v, master):
            g = g * scale_f
            m2 = beta1 * m + (1 - beta1) * g
            v2 = beta2 * v + (1 - beta2) * g * g
            update = (m2 / b1c) / (jnp.sqrt(v2 / b2c) + 1e-8)
            master2 = master * (1 - lr * weight_decay) - lr * update
            return m2, v2, master2

        updated = jax.tree_util.tree_map(
            upd, g32, opt_state["m"], opt_state["v"], opt_state["master"])
        flat, treedef = jax.tree_util.tree_flatten(
            updated, is_leaf=lambda xx: isinstance(xx, tuple))
        new_m = jax.tree_util.tree_unflatten(treedef, [tt[0] for tt in flat])
        new_v = jax.tree_util.tree_unflatten(treedef, [tt[1] for tt in flat])
        new_w = jax.tree_util.tree_unflatten(treedef, [tt[2] for tt in flat])
        new_params = jax.tree_util.tree_map(
            lambda w, pp_: w.astype(pp_.dtype), new_w, params)
        return loss, new_params, {"step": step, "m": new_m, "v": new_v, "master": new_w}

    opt_shardings = {
        "step": NamedSharding(mesh, P()),
        "m": param_shardings,
        "v": param_shardings,
        "master": param_shardings,
    }
    data_sharding = NamedSharding(mesh, data_spec)
    label_sharding = NamedSharding(mesh, P(("dp", "sharding")))
    jitted = jax.jit(
        train_step,
        in_shardings=(param_shardings, opt_shardings, data_sharding,
                      label_sharding, NamedSharding(mesh, P())),
        out_shardings=(NamedSharding(mesh, P()), param_shardings, opt_shardings),
        donate_argnums=(0, 1),
    )
    # fresh zeros in opt state don't inherit param shardings — pin them
    opt_init = jax.jit(opt_init, out_shardings=opt_shardings)
    return jitted, opt_init, param_shardings, data_sharding


def count_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
