"""Llama family (BASELINE config #3; north-star Llama-3-8B pretrain).

Reference recipe surface: PaddleNLP llm/ on top of the reference framework's
fused ops (fused_rms_norm, fused_rotary_position_embedding, swiglu — see
python/paddle/incubate/nn/functional/) and fleet hybrid parallelism.

TPU-first design:
- the eager Layer graph (LlamaForCausalLM) is the UX/debug surface;
- the *training path* is :func:`build_train_step` — a pure pjit-compiled
  function over a named mesh ("dp", "sharding"/zero, "mp"/tensor, "sep"/context)
  where every weight carries a PartitionSpec (Megatron-style column/row splits
  over "mp"), activations shard batch over "dp" and sequence over "sep", and
  GSPMD inserts the all-reduces/all-gathers the reference does with NCCL.
- attention = Pallas flash attention (ops/pallas/flash_attention.py);
  rms_norm/rope/swiglu = fused kernels from ops/pallas.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas import flash_attention as fa
from ..ops.pallas import rms_norm as rms
from ..ops.pallas import rope as rope_mod
from ..ops.pallas import swiglu as swiglu_mod


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def llama3_8b():
        return LlamaConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        )

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, inter=128, seq=128):
        return LlamaConfig(
            vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
            num_hidden_layers=layers, num_attention_heads=heads,
            num_key_value_heads=kv_heads, max_position_embeddings=seq,
        )


# ---------------------------------------------------------------------------
# pure functional core (the pjit training path)
# ---------------------------------------------------------------------------

def init_params(cfg: LlamaConfig, key=None) -> dict:
    """Parameter pytree.  Layer weights are stacked over a leading layer dim so
    the transformer stack runs as one lax.scan (single compiled block, fast
    compile, and the natural shape for pipeline stacking over 'pp')."""
    key = key if key is not None else jax.random.key(0)
    k = iter(jax.random.split(key, 16))
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    nh, nkv, hd, L = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim, cfg.num_hidden_layers
    std = 0.02

    def init(kk, shape):
        return (jax.random.normal(kk, shape, jnp.float32) * std).astype(cfg.dtype)

    params = {
        "embed": init(next(k), (v, h)),
        "final_norm": jnp.ones((h,), cfg.dtype),
        "layers": {
            "input_norm": jnp.ones((L, h), cfg.dtype),
            "post_norm": jnp.ones((L, h), cfg.dtype),
            "wq": init(next(k), (L, h, nh * hd)),
            "wk": init(next(k), (L, h, nkv * hd)),
            "wv": init(next(k), (L, h, nkv * hd)),
            "wo": init(next(k), (L, nh * hd, h)),
            "w_gate": init(next(k), (L, h, i)),
            "w_up": init(next(k), (L, h, i)),
            "w_down": init(next(k), (L, i, h)),
        },
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = init(next(k), (h, v))
    return params


def param_specs(cfg: LlamaConfig, pp: bool = False, mp: int = 1) -> dict:
    """PartitionSpecs = the Megatron TP sharding map of the reference's mp_layers
    (ColumnParallelLinear splits output dim over 'mp', RowParallelLinear splits
    input dim; VocabParallelEmbedding splits vocab), plus ZeRO over 'sharding'
    on the other dim (fleet sharding stage 3 analog).  With ``pp`` the stacked
    layer dim is sharded over the 'pp' mesh axis — each device holds one
    pipeline stage's contiguous layer slice (the PipelineLayer segmentation of
    pp_layers.py:258, realized as a sharding).

    GQA under TP: when ``mp`` exceeds ``num_key_value_heads``, K/V projections
    are REPLICATED over 'mp' instead of column-sharded — a sub-head split
    makes the SPMD partitioner replicate-then-repartition every layer
    ("involuntary full rematerialization", wasted ICI bandwidth).  The
    reference's mp_layers duplicate KV heads in exactly this regime
    (fleet/layers/mpu/mp_layers.py:49,336)."""
    layer_dim = "pp" if pp else None
    # replicate unless mp divides the kv heads evenly (mp > kv_heads is the
    # common case, but any non-dividing mp sub-head-splits too)
    kv_col = None if cfg.num_key_value_heads % mp != 0 else "mp"

    def mat(name):
        # column/row assignment comes from the shared Megatron table
        # (MEGATRON_SPLIT) — the same one serving_param_specs reads
        tensor = kv_col if name in ("wk", "wv") else "mp"
        if MEGATRON_SPLIT[name] == "col":
            return P(layer_dim, "sharding", tensor)
        return P(layer_dim, tensor, "sharding")

    return {
        "embed": P("mp", "sharding"),          # vocab-parallel embedding
        "final_norm": P(None),
        "layers": {
            "input_norm": P(layer_dim, None),
            "post_norm": P(layer_dim, None),
            **{name: mat(name) for name in MEGATRON_SPLIT},
        },
        "lm_head": P("sharding", "mp"),
    }


#: the Megatron split per decoder matmul leaf — the ONE table the training
#: specs above and the serving TP specs below both read, so the two spec
#: surfaces cannot disagree about which dim a weight shards on.
#: 'col' = ColumnParallelLinear (output dim over the tensor axis),
#: 'row' = RowParallelLinear (input dim over the tensor axis).
MEGATRON_SPLIT = {"wq": "col", "wk": "col", "wv": "col",
                  "w_gate": "col", "w_up": "col",
                  "wo": "row", "w_down": "row"}


def serving_param_specs(cfg: LlamaConfig, quant: str | None = None,
                        axis: str = "tp") -> dict:
    """PartitionSpecs for the SERVING param tree over a 1-D ``(axis,)`` mesh
    (docs/tp_serving.md) — the continuous-batching engine's
    ``tensor_parallel=N`` mode.

    Unlike the training map (:func:`param_specs`), serving keeps the
    residual stream, embedding, norms and lm_head REPLICATED: every shard
    computes the full [B, V] logits row identically, so the sampler and the
    host scheduler see exactly the single-chip values and the only
    cross-shard traffic is the two per-layer psums
    (:func:`decoder_attn_residual` / :func:`decoder_mlp_residual`).
    Column-parallel leaves split heads/ffn on their OUTPUT dim, row-parallel
    ones their INPUT dim (:data:`MEGATRON_SPLIT`); K/V projections split
    along kv_heads — the same axis the paged KV pool shards on, which is
    what keeps the paged-attention kernels' page walk shard-local.

    ``quant`` (None | 'int8' | 'int4'): the engine's weight-only mode stores
    matmul leaves as ``{'qweight': [L, out, in], 'scale': [L, out]}``
    (nn/quant layout) — the split dim maps through the transpose, and a
    row-parallel leaf's per-out-channel scales replicate so dequant-on-read
    stays shard-local."""
    def leaf(name):
        split = MEGATRON_SPLIT.get(name)
        if split == "col":
            return ({"qweight": P(None, axis, None), "scale": P(None, axis)}
                    if quant else P(None, None, axis))
        if split == "row":
            return ({"qweight": P(None, None, axis), "scale": P(None, None)}
                    if quant else P(None, axis, None))
        return P()      # norms: replicated
    specs = {
        "embed": P(),
        "final_norm": P(),
        "layers": {k: leaf(k) for k in
                   ("input_norm", "post_norm", "wq", "wk", "wv", "wo",
                    "w_gate", "w_up", "w_down")},
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P()
    return specs


def _tp_psum(y, tp_axis, scope):
    """The tensor-parallel all-reduce boundary.  ``tp_axis=None`` is the
    single-chip path (no collective, byte-identical program); with an axis
    name the caller is inside a shard_map region holding a row-parallel
    partial sum.  The named scope lands in HLO op_name metadata so the
    analysis resharding rule can allowlist exactly these two collectives
    per layer and flag everything else (docs/tp_serving.md)."""
    if tp_axis is None:
        return y
    with jax.named_scope(scope):
        return jax.lax.psum(y, tp_axis)


def decoder_attn_residual(x, attn, lp, wmat=None, tp_axis=None):
    """Attention output projection + residual — ONE home for serving
    (inference.transformer_apply) and training (``_layer_forward`` here and
    in moe_llama), so the Megatron row-parallel contract cannot drift:
    ``wo`` splits its INPUT (heads) dim over tp, each shard's
    ``attn_local @ wo_local`` is a partial sum, and the psum here is TP
    boundary 1 of the layer's exactly-two.  ``wmat(leaf, dtype)`` resolves
    weight-only-quantized leaves (serving); None reads the leaf raw."""
    wo = lp["wo"] if wmat is None else wmat(lp["wo"], x.dtype)
    return x + _tp_psum(attn @ wo, tp_axis, "tp_allreduce_attn_out")


def decoder_mlp_residual(cfg, x, lp, wmat=None, tp_axis=None):
    """post-norm + swiglu MLP + residual, the layer's second half and TP
    boundary 2: w_gate/w_up are column-parallel (each shard computes a
    ffn/tp slice), w_down row-parallel, and the psum completes the down
    projection.  Shared by serving and training like
    :func:`decoder_attn_residual`."""
    w = (lambda n: lp[n] if wmat is None else wmat(lp[n], x.dtype))
    xn = rms.rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
    y = swiglu_mod.swiglu(xn @ w("w_gate"), xn @ w("w_up")) @ w("w_down")
    return x + _tp_psum(y, tp_axis, "tp_allreduce_mlp_out")


def decoder_layer_tail(cfg, x, attn, lp, wmat=None, tp_axis=None,
                       mlp_fn=None):
    """The whole post-attention half of a decoder layer — attention output
    projection, TP psum boundary 1, residual add, post-norm + swiglu MLP,
    TP psum boundary 2, residual add — in ONE seam shared by serving and
    training (the stage-2 megastep seam; docs/paged_attention.md
    "Megastep stage 2").

    ``mlp_fn=None`` composes :func:`decoder_attn_residual` +
    :func:`decoder_mlp_residual` exactly — byte-identical to calling the
    two halves directly, which is what training and every unfused serving
    program keep tracing.  With ``mlp_fn(h_res, attn_y, lp) -> (h1, y)``
    the residual add + post RMSNorm + SwiGLU MLP between the two psum
    boundaries run through the caller's fused implementation (the serving
    decode path passes ops/pallas/paged_attention.fused_layer_mlp here):
    ``h1 = h_res + attn_y`` is the layer's next residual anchor and ``y``
    the UN-reduced down projection, so the two all-reduces stay exactly
    where PR 7 put them — the only per-layer exits of the fused decode
    layer."""
    if mlp_fn is None:
        x = decoder_attn_residual(x, attn, lp, wmat=wmat, tp_axis=tp_axis)
        return decoder_mlp_residual(cfg, x, lp, wmat=wmat, tp_axis=tp_axis)
    wo = lp["wo"] if wmat is None else wmat(lp["wo"], x.dtype)
    attn_y = _tp_psum(attn @ wo, tp_axis, "tp_allreduce_attn_out")
    h1, y = mlp_fn(x, attn_y, lp)
    return h1 + _tp_psum(y, tp_axis, "tp_allreduce_mlp_out")


def _layer_forward(cfg: LlamaConfig, x, layer_params, cos, sin, use_flash=True,
                   attn_fn=None):
    """One transformer block; x: [b, s, h].  ``attn_fn(q, k, v) -> out`` (all
    BSHD) overrides the attention implementation — used by the context-parallel
    path to route through ring attention over the 'sep' axis."""
    lp = layer_params
    b, s, h = x.shape
    nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim

    # attention
    xn = rms.rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
    q = (xn @ lp["wq"]).reshape(b, s, nh, hd)
    kk = (xn @ lp["wk"]).reshape(b, s, nkv, hd)
    vv = (xn @ lp["wv"]).reshape(b, s, nkv, hd)
    q, kk = rope_mod.apply_rotary_pos_emb(q, kk, cos, sin)
    if attn_fn is not None:
        attn = attn_fn(q, kk, vv)
    elif use_flash:
        attn = fa.flash_attention_bshd(q, kk, vv, causal=True)
    else:
        attn = fa._composed_attention(q, kk, vv, None, True, 1.0 / math.sqrt(hd))
    # the shared post-attention seam (mlp_fn=None: the exact two-half
    # composition serving's unfused programs and TP both pin)
    return decoder_layer_tail(cfg, x, attn.reshape(b, s, nh * hd), lp)


def _embed_rope(cfg: LlamaConfig, params, input_ids):
    """Shared prelude: token embedding + rope tables for the sequence length."""
    x = jnp.take(params["embed"], input_ids, axis=0).astype(cfg.dtype)
    cos, sin = rope_mod.rope_cos_sin(
        x.shape[1], cfg.head_dim, base=cfg.rope_theta, dtype=cfg.dtype)
    return x, cos, sin


def _norm_and_head(cfg: LlamaConfig, params, x):
    """Final rms_norm + resolved (possibly tied) lm head weight — the single
    source for head tying/dtype, shared by the dense and chunked losses."""
    xn = rms.rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T.astype(cfg.dtype)
    return xn, head


def _final_head(cfg: LlamaConfig, params, x):
    """Shared tail: final rms_norm + (possibly tied) lm head."""
    xn, head = _norm_and_head(cfg, params, x)
    return xn @ head


def sep_attention(mesh: Mesh, axis: str = "sep", impl: str = "ring"):
    """Context-parallel attention over the mesh's sequence axis (the reference's
    sep axis + SegmentParallel, segment_parallel.py:26; flash-attention SPMD
    rule with sharded seq, spmd_rules/flash_attention.cc).

    Returns an ``attn_fn(q, k, v)`` (BSHD) that binds the 'sep' axis with a
    partial-manual shard_map — only 'sep' goes manual, dp/mp/sharding stay
    GSPMD-auto — and runs ring attention (K/V blocks rotating over ICI with
    ppermute) or Ulysses (all_to_all heads<->seq) on the local shards."""
    from ..ops import ring_attention as ra

    seq_spec = P(None, axis, None, None)

    def attn_fn(q, k, v):
        def local(q_, k_, v_):
            if impl == "ulysses":
                return ra.ulysses_attention(q_, k_, v_, axis_name=axis, causal=True)
            return ra.ring_attention(q_, k_, v_, axis_name=axis, causal=True)

        return jax.shard_map(
            local, mesh=mesh, in_specs=(seq_spec,) * 3, out_specs=seq_spec,
            axis_names={axis}, check_vma=False,
        )(q, k, v)

    return attn_fn


def _remat_wrap(body, remat):
    """Apply the recompute policy (reference: fleet/recompute full-block
    recompute vs selective recompute).  PADDLE_TPU_REMAT selects at trace
    time: 'full' (default — recompute everything, minimum HBM), 'dots'
    (save matmul outputs, recompute only cheap elementwise — trades HBM for
    fewer recomputed MXU FLOPs), 'none' (no recompute)."""
    import os

    if not remat:
        return body
    policy = os.environ.get("PADDLE_TPU_REMAT", "full")
    if policy == "none":
        return body
    if policy == "dots":
        return jax.checkpoint(
            body, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    return jax.checkpoint(body)


def forward(cfg: LlamaConfig, params, input_ids, use_flash=True, remat=True,
            attn_fn=None, return_hidden=False):
    """Logits for [b, s] token ids.  The layer stack is a lax.scan over the
    stacked layer weights with jax.checkpoint (activation recompute ≙ the
    reference's recompute_sequential over transformer blocks).
    ``return_hidden`` skips the final norm + lm head and returns the last
    hidden states (the chunked-xent loss fuses the head into the loss)."""
    x, cos, sin = _embed_rope(cfg, params, input_ids)

    def body(carry, lp):
        out = _layer_forward(cfg, carry, lp, cos, sin, use_flash, attn_fn)
        return out, None

    scan_body = _remat_wrap(body, remat)
    x, _ = jax.lax.scan(scan_body, x, params["layers"])
    if return_hidden:
        return x
    return _final_head(cfg, params, x)


def forward_pp(cfg: LlamaConfig, params, input_ids, mesh, num_microbatches,
               use_flash=True, remat=True, sep_attn_impl="ring",
               return_hidden=False):
    """Pipeline-parallel forward: the stacked layer dim is sharded over 'pp'
    and executed by the in-jit GPipe engine (fleet/pipeline.py gpipe_stacked ≙
    the reference's PipelineParallel.forward_backward_pipeline at
    pipeline_parallel.py:684, as one compiled SPMD program).

    When the mesh also has 'sep' > 1, sep is bound manually in the SAME region
    (sdy cannot nest partial-manual regions): microbatches and rope tables are
    seq-sharded over 'sep' and attention runs ring/Ulysses directly."""
    from ..distributed.fleet.pipeline import gpipe_stacked
    from ..ops import ring_attention as ra

    sep = dict(mesh.shape).get("sep", 1)
    x, cos, sin = _embed_rope(cfg, params, input_ids)
    b, s, h = x.shape
    M = num_microbatches
    assert b % M == 0, f"batch {b} not divisible by num_microbatches {M}"
    xm = x.reshape(M, b // M, s, h)

    if sep > 1:
        if sep_attn_impl == "ulysses":
            attn_fn = lambda q, k, v: ra.ulysses_attention(q, k, v, axis_name="sep", causal=True)
        else:
            attn_fn = lambda q, k, v: ra.ring_attention(q, k, v, axis_name="sep", causal=True)
        gp_kw = dict(
            mb_spec=P(None, None, "sep", None),
            extra_specs=(P(None, "sep", None),) * 2,  # rope [1, s, d]: local slices
            manual_axes=("sep",),
        )
    else:
        attn_fn = None
        gp_kw = {}

    def stage_fn(stage_params, xin, cos_, sin_):
        def body(carry, lp):
            return _layer_forward(cfg, carry, lp, cos_, sin_, use_flash, attn_fn), None

        scan_body = _remat_wrap(body, remat)
        y, _ = jax.lax.scan(scan_body, xin, stage_params)
        return y

    outs = gpipe_stacked(stage_fn, params["layers"], xm, mesh, "pp",
                         extra_args=(cos, sin), **gp_kw)
    if return_hidden:
        return outs.reshape(b, s, h)
    return _final_head(cfg, params, outs.reshape(b, s, h))


def loss_and_grads_1f1b(cfg: LlamaConfig, params, input_ids, labels, mesh,
                        num_microbatches, use_flash=True, remat=True,
                        num_chunks=1, layers_stage_major=False,
                        zero_bubble=False, sep_attn_impl="ring"):
    """Pipeline train-step core on the executed 1F1B schedule
    (fleet/pipeline.py one_f_one_b_stacked ≙ pipeline_parallel.py:684 run,
    not simulated).  Stage 0 owns the embedding, the last stage owns final
    norm + lm head + loss, so loss cotangents stream backward per microbatch.
    With ``num_chunks`` C > 1 this is the interleaved/VPP schedule
    (PipelineParallelWithInterleave, pipeline_parallel.py:1308): the stacked
    layers are reordered stage-major (stage s owns virtual stages c·P+s) so
    the pp shard of each stage holds its C chunks; grads are reordered back.
    That in-step reorder reshards ~half the layer params across pp shards
    each step — callers that keep their train state stage-major permanently
    (reorder once at init) should pass ``layers_stage_major=True`` to skip
    both permutes.  Returns (mean_loss, grads) with grads matching the
    params tree (f32)."""
    from ..distributed.fleet.pipeline import one_f_one_b_stacked
    from ..ops import ring_attention as ra

    b, s = input_ids.shape
    M = num_microbatches
    assert b % M == 0, f"batch {b} not divisible by num_microbatches {M}"
    ids_m = input_ids.reshape(M, b // M, s)
    lbl_m = labels.reshape(M, b // M, s)
    cos, sin = rope_mod.rope_cos_sin(s, cfg.head_dim, base=cfg.rope_theta,
                                     dtype=cfg.dtype)
    C = num_chunks
    pp_deg = dict(mesh.shape).get("pp", 1)
    sep = dict(mesh.shape).get("sep", 1)
    L = cfg.num_hidden_layers
    assert L % (pp_deg * C) == 0, (L, pp_deg, C)
    Lv = L // (pp_deg * C)  # layers per virtual stage

    # sep > 1: the runner binds 'sep' manually in the same region (mirrors
    # the gpipe region, forward_pp) — sequence-sharded microbatches + rope
    # slices, ring/Ulysses attention inside each stage
    if sep > 1:
        if sep_attn_impl == "ulysses":
            attn_fn = lambda q, k, v: ra.ulysses_attention(
                q, k, v, axis_name="sep", causal=True)
        else:
            attn_fn = lambda q, k, v: ra.ring_attention(
                q, k, v, axis_name="sep", causal=True)
    else:
        attn_fn = None

    def embed_fn(ep, ids, cos_, sin_):
        return jnp.take(ep, ids, axis=0).astype(cfg.dtype)

    def _scan_layers(sp, x, cos_, sin_):
        def body(carry, lp):
            return _layer_forward(cfg, carry, lp, cos_, sin_, use_flash,
                                  attn_fn), None

        scan_body = _remat_wrap(body, remat)
        y, _ = jax.lax.scan(scan_body, x, sp)
        return y

    if C == 1:
        stage_fn = _scan_layers
    else:
        def stage_fn(sp, x, chunk, cos_, sin_):
            # local stacked leaves hold C chunks of Lv layers (stage-major
            # layout): slice this chunk, then scan it
            pick = lambda w: jax.lax.dynamic_index_in_dim(
                w.reshape((C, Lv) + w.shape[1:]), chunk, 0, keepdims=False)
            return _scan_layers(jax.tree_util.tree_map(pick, sp), x, cos_, sin_)

    def _to_vpp(tree):
        # natural layer order [V·Lv, ...] -> stage-major [P·(C·Lv), ...]
        return jax.tree_util.tree_map(
            lambda w: w.reshape((C, pp_deg, Lv) + w.shape[1:])
                       .swapaxes(0, 1).reshape(w.shape), tree)

    def _from_vpp(tree):
        return jax.tree_util.tree_map(
            lambda w: w.reshape((pp_deg, C, Lv) + w.shape[1:])
                       .swapaxes(0, 1).reshape(w.shape), tree)

    tied = "lm_head" not in params

    def head_loss_fn(hp, y, lbl, cos_, sin_):
        # hp carries exactly the keys _norm_and_head reads ('final_norm' +
        # 'embed' or 'lm_head'), so the head path stays single-sourced;
        # head_xent honors PADDLE_TPU_XENT_CHUNK per microbatch
        return head_xent(cfg, hp, y, lbl)

    head_params = {"final_norm": params["final_norm"]}
    head_params["embed" if tied else "lm_head"] = (
        params["embed"] if tied else params["lm_head"])

    # bind dp+sharding manually alongside pp when either is nontrivial: the
    # batch dim tuple-sharded over two auto axes CHECK-fails the partitioner
    # (the round-3 north-star blocker) — and manual ZeRO gathers make the
    # sharding-axis flow explicit (see one_f_one_b_stacked docstring)
    mesh_axes = dict(mesh.shape)
    batch_axes = tuple(a for a in ("dp", "sharding") if mesh_axes.get(a, 1) > 1)
    pipe_kw = {}
    if batch_axes:
        specs = param_specs(cfg, pp=True, mp=mesh_axes.get("mp", 1))
        head_specs = {"final_norm": specs["final_norm"]}
        head_specs["embed" if tied else "lm_head"] = (
            specs["embed"] if tied else specs["lm_head"])
        pipe_kw = dict(batch_axes=batch_axes,
                       zero_axis="sharding" if "sharding" in batch_axes else None,
                       embed_specs=specs["embed"],
                       stacked_specs=specs["layers"], head_specs=head_specs)

    if sep > 1:
        pipe_kw["seq_axis"] = "sep"
        pipe_kw["extra_specs"] = (P(None, "sep", None),) * 2  # rope [1, s, d]

    reorder = C > 1 and not layers_stage_major
    stacked = _to_vpp(params["layers"]) if reorder else params["layers"]
    loss, (dep, dsp, dhp) = one_f_one_b_stacked(
        embed_fn, stage_fn, head_loss_fn,
        params["embed"], stacked, head_params,
        ids_m, lbl_m, mesh, axis_name="pp", extra_args=(cos, sin),
        num_chunks=C, zero_bubble=zero_bubble, **pipe_kw)
    if reorder:
        dsp = _from_vpp(dsp)

    grads = {"final_norm": dhp["final_norm"], "layers": dsp}
    grads["embed"] = dep + dhp["embed"] if tied else dep
    if not tied:
        grads["lm_head"] = dhp["lm_head"]
    return loss, grads


def _xent(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def _xent_chunk_env() -> int:
    """``PADDLE_TPU_XENT_CHUNK=<positions>`` (read at trace time, like
    PADDLE_TPU_REMAT): sequence-chunked cross-entropy.  0/unset = off."""
    raw = os.environ.get("PADDLE_TPU_XENT_CHUNK", "0")
    try:
        return int(raw)
    except ValueError:
        # a typo silently disabling chunking would resurface the exact OOM
        # the flag exists to prevent
        raise ValueError(
            f"PADDLE_TPU_XENT_CHUNK must be an integer, got {raw!r}") from None


def head_xent(cfg: LlamaConfig, params, x, labels, chunk=None):
    """final_norm + lm head + cross entropy, optionally WITHOUT materializing
    the full [b, s, V] f32 logits: with ``chunk`` set (or the
    PADDLE_TPU_XENT_CHUNK env), the head matmul + log_softmax run per
    sequence chunk inside a rematerialized lax.scan, so peak logits memory
    drops from b*s*V*4 bytes to b*chunk*V*4 (2.1 GB -> 0.5 GB for the
    bench's xl rung) at the cost of recomputing chunk logits in the
    backward — the standard memory/FLOPs trade for big-vocab heads.
    Numerics are identical (per-position log_softmax is independent)."""
    chunk = _xent_chunk_env() if chunk is None else int(chunk)
    b, s, h = x.shape
    if chunk <= 0 or s <= chunk or s % chunk:
        return _xent(_final_head(cfg, params, x), labels)
    xn, head = _norm_and_head(cfg, params, x)
    n = s // chunk
    xc = xn.reshape(b, n, chunk, h).swapaxes(0, 1)      # [n, b, chunk, h]
    lc = labels.reshape(b, n, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def step(tot, xl):
        xck, lbl = xl
        logp = jax.nn.log_softmax((xck @ head).astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(logp, lbl[..., None], axis=-1)[..., 0]
        return tot + picked.sum(), None

    tot, _ = jax.lax.scan(step, jnp.float32(0), (xc, lc))
    return -tot / (b * s)


def loss_fn(cfg: LlamaConfig, params, input_ids, labels, attn_fn=None):
    if _xent_chunk_env() > 0:
        x = forward(cfg, params, input_ids, attn_fn=attn_fn,
                    return_hidden=True)
        return head_xent(cfg, params, x, labels)
    return _xent(forward(cfg, params, input_ids, attn_fn=attn_fn), labels)


def loss_fn_pp(cfg: LlamaConfig, params, input_ids, labels, mesh, num_microbatches,
               sep_attn_impl="ring"):
    if _xent_chunk_env() > 0:
        x = forward_pp(cfg, params, input_ids, mesh, num_microbatches,
                       sep_attn_impl=sep_attn_impl, return_hidden=True)
        return head_xent(cfg, params, x, labels)
    logits = forward_pp(cfg, params, input_ids, mesh, num_microbatches,
                        sep_attn_impl=sep_attn_impl)
    return _xent(logits, labels)


def make_mesh(dp=1, mp=1, sharding=1, sep=1, pp=1, devices=None):
    """Build the hybrid mesh with the reference's canonical axis set."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = dp * mp * sharding * sep * pp
    assert devices.size >= n, f"need {n} devices, have {devices.size}"
    arr = devices[:n].reshape(dp, pp, sharding, sep, mp)
    return Mesh(arr, axis_names=("dp", "pp", "sharding", "sep", "mp"))


def build_train_step(cfg: LlamaConfig, mesh: Mesh, lr=3e-4, weight_decay=0.1,
                     beta1=0.9, beta2=0.95, grad_clip=1.0, num_microbatches=None,
                     sep_attn_impl="ring", pipeline_schedule=None,
                     num_chunks=None):
    """The pjit-compiled train step: forward+backward+AdamW, all sharded.

    Data: [b, s] sharded ('dp'+'sharding' on batch, 'sep' on sequence).
    GSPMD propagates the Megatron weight specs through the scan; gradient psum
    over 'dp' and optimizer-state sharding over 'sharding' (ZeRO-1/2) come out
    of the same spec algebra — no per-op SPMD rules needed (SURVEY.md §3.4).
    When the mesh carries a 'pp' axis > 1, the layer stack is staged over it
    and the forward runs through the in-jit GPipe engine with
    ``num_microbatches`` (default: pp size) microbatches.  When 'sep' > 1,
    attention routes through ring attention over the sep axis
    (``sep_attn_impl``: 'ring' or 'ulysses') with the sequence sharded."""
    pp = dict(mesh.shape).get("pp", 1)
    sep = dict(mesh.shape).get("sep", 1)
    if pp > 1:
        assert cfg.num_hidden_layers % pp == 0, (
            f"{cfg.num_hidden_layers} layers not divisible by pp={pp}")
        num_microbatches = num_microbatches or pp
    # pp>1 binds sep inside its own manual region (forward_pp); otherwise wrap
    # attention in its own sep shard_map
    attn_fn = sep_attention(mesh, "sep", sep_attn_impl) if sep > 1 and pp == 1 else None
    specs = param_specs(cfg, pp=pp > 1, mp=dict(mesh.shape).get("mp", 1))
    data_spec = P(("dp", "sharding"), "sep")

    def to_named(tree_specs):
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), tree_specs,
            is_leaf=lambda s: isinstance(s, P),
        )

    param_shardings = to_named(specs)

    def opt_init(params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            "v": jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            # master fp32 weights (multi_precision AdamW semantics)
            "master": jax.tree_util.tree_map(lambda p: p.astype(jnp.float32), params),
            # last step's pre-clip grad global-norm: free to export (it is
            # already computed for clipping) and the multichip dryrun's
            # numerics fingerprint — loss ≈ ln(vocab) at init cannot
            # distinguish right from wrong backward compute
            "gnorm": jnp.zeros((), jnp.float32),
        }

    # the executed-1F1B runner binds 'pp' plus any nontrivial dp/sharding
    # axes manually (loss_and_grads_1f1b), and since round 5 also a 'sep'
    # axis (seq-sharded microbatches + ring attention inside each stage —
    # the reference's 1F1B runtime composes with sep the same way,
    # pipeline_parallel.py:684 + topology.py:77).
    # 'vpp'/'interleave' runs the same executed runner with C>1 virtual
    # chunks per stage (num_chunks); '1f1b' is C=1; 'zb'/'zero_bubble' is
    # the executed ZB-H1 (deferred weight grads fill the drain bubble —
    # needs num_microbatches >= 2*(pp-1)+1)
    # None = auto (executed 1F1B when pp > 1); ANY explicit request that
    # can't run here raises — a schedule silently different from the
    # configured one is worse than an error
    schedule = "1f1b" if pipeline_schedule is None else pipeline_schedule
    # eager_1f1b runs the executed 1F1B clock: its deeper warmup exists to
    # overlap p2p sends with compute, which inside one jitted SPMD program
    # is already the XLA latency-hiding scheduler's job (see
    # schedule_eager_1f1b's spec oracle in fleet/pipeline.py)
    known = ("1f1b", "eager_1f1b", "vpp", "interleave", "zb", "zero_bubble",
             "gpipe", "fthenb")
    if schedule not in known:
        raise ValueError(f"unknown pipeline_schedule {schedule!r} "
                         f"(expected one of {known})")
    use_1f1b = pp > 1 and schedule in (
        "1f1b", "eager_1f1b", "vpp", "interleave", "zb", "zero_bubble")
    zb = schedule in ("zb", "zero_bubble")
    if pipeline_schedule is not None:
        if schedule in ("gpipe", "fthenb"):
            if pp <= 1:
                raise ValueError(
                    f"pipeline_schedule={pipeline_schedule!r} needs a mesh "
                    f"with pp > 1 (got pp={pp})")
        elif not use_1f1b:
            raise ValueError(
                f"pipeline_schedule={pipeline_schedule!r} needs a mesh with "
                f"pp > 1 (got pp={pp})")
    if num_chunks is not None and num_chunks > 1 and not (
            schedule in ("vpp", "interleave")):
        raise ValueError(
            f"num_chunks={num_chunks} requires pipeline_schedule="
            f"'vpp'/'interleave', got {schedule!r}")
    vpp_chunks = ((num_chunks or 2)
                  if schedule in ("vpp", "interleave") else 1)

    def train_step(params, opt_state, input_ids, labels):
        if use_1f1b:
            loss, grads = loss_and_grads_1f1b(cfg, params, input_ids, labels,
                                              mesh, num_microbatches,
                                              num_chunks=vpp_chunks,
                                              zero_bubble=zb,
                                              sep_attn_impl=sep_attn_impl)
        else:
            if pp > 1:
                lfn = lambda p: loss_fn_pp(cfg, p, input_ids, labels, mesh,
                                           num_microbatches, sep_attn_impl)
            else:
                lfn = lambda p: loss_fn(cfg, p, input_ids, labels, attn_fn)
            loss, grads = jax.value_and_grad(lfn)(params)
        g32 = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        # global-norm clip (HybridParallelClipGrad semantics; psum over all axes
        # is implicit — the sharded sum-of-squares reduces globally under GSPMD)
        leaves = jax.tree_util.tree_leaves(g32)
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
        scale_f = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-6))
        step = opt_state["step"] + 1
        b1c = 1 - beta1**step.astype(jnp.float32)
        b2c = 1 - beta2**step.astype(jnp.float32)

        def upd(g, m, v, master):
            g = g * scale_f
            m2 = beta1 * m + (1 - beta1) * g
            v2 = beta2 * v + (1 - beta2) * g * g
            update = (m2 / b1c) / (jnp.sqrt(v2 / b2c) + 1e-8)
            master2 = master * (1 - lr * weight_decay) - lr * update
            return m2, v2, master2

        flat_g, treedef = jax.tree_util.tree_flatten(g32)
        flat_m = treedef.flatten_up_to(opt_state["m"])
        flat_v = treedef.flatten_up_to(opt_state["v"])
        flat_w = treedef.flatten_up_to(opt_state["master"])
        new_m, new_v, new_w = [], [], []
        for g, m, v, w in zip(flat_g, flat_m, flat_v, flat_w):
            m2, v2, w2 = upd(g, m, v, w)
            new_m.append(m2)
            new_v.append(v2)
            new_w.append(w2)
        unf = lambda leaves_: jax.tree_util.tree_unflatten(treedef, leaves_)
        new_params = jax.tree_util.tree_map(
            lambda w, p: w.astype(p.dtype), unf(new_w), params
        )
        new_opt = {"step": step, "m": unf(new_m), "v": unf(new_v),
                   "master": unf(new_w), "gnorm": gnorm}
        return loss, new_params, new_opt

    opt_shardings = {
        "step": NamedSharding(mesh, P()),
        "m": param_shardings,
        "v": param_shardings,
        "master": param_shardings,
        "gnorm": NamedSharding(mesh, P()),
    }
    data_sharding = NamedSharding(mesh, data_spec)
    jitted = jax.jit(
        train_step,
        in_shardings=(param_shardings, opt_shardings, data_sharding, data_sharding),
        out_shardings=(NamedSharding(mesh, P()), param_shardings, opt_shardings),
        donate_argnums=(0, 1),
    )
    # fresh zeros in the opt state don't inherit param shardings — pin them so
    # opt_init output always matches the step's in_shardings
    opt_init = jax.jit(opt_init, out_shardings=opt_shardings)
    return jitted, opt_init, param_shardings, data_sharding


def flops_per_token(cfg: LlamaConfig) -> float:
    """Training FLOPs/token ≈ 6 * active params + attention quadratic term."""
    h, i, v, L = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size, cfg.num_hidden_layers
    nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    per_layer = h * (nh * hd) + 2 * h * (nkv * hd) + (nh * hd) * h + 3 * h * i
    dense = L * per_layer + v * h  # + embed (lookup free)
    return 6.0 * dense


def attn_flops_per_token(cfg: LlamaConfig, seq: int, causal: bool = True) -> float:
    # 2 matmuls of [s, hd] x [hd, s] per head, fwd+bwd(2x) => 6 * 2 * s * hd * nh.
    # Causal attention only computes the lower triangle — the flash kernel
    # skips above-diagonal blocks — so the average effective kv length per
    # query is (s+1)/2, not s.  Counting the full square would overstate
    # achieved FLOPs (VERDICT r2 weak #5).
    eff = (seq + 1) / 2.0 if causal else float(seq)
    return 6.0 * 2.0 * eff * cfg.head_dim * cfg.num_attention_heads * cfg.num_hidden_layers


def count_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


# ---------------------------------------------------------------------------
# eager Layer surface (paddle-style UX over the same functional core)
# ---------------------------------------------------------------------------

from ..core.tensor import Parameter, Tensor, apply_op, _unwrap  # noqa: E402
from ..nn.layer_base import Layer  # noqa: E402


class LlamaModel(Layer):
    """Eager wrapper: parameters are paddle Tensors; forward dispatches the
    functional core through the tape (so .backward()/optimizers work), and the
    same weights feed build_train_step for the pjit path."""

    def __init__(self, config: LlamaConfig, seed: int = 0):
        super().__init__()
        self.config = config
        raw = init_params(config, jax.random.key(seed))
        self._tree_names = []
        flat, self._treedef = jax.tree_util.tree_flatten_with_path(raw)
        for path, val in flat:
            name = "_".join(str(getattr(p, "key", p)) for p in path)
            self.add_parameter(name, Parameter(val))
            self._tree_names.append(name)

    def _params_tree(self, vals=None):
        leaves = [
            self._parameters[n]._value if vals is None else vals[i]
            for i, n in enumerate(self._tree_names)
        ]
        import jax.tree_util as jtu

        return jtu.tree_unflatten(jtu.tree_structure(init_spec_like(self.config)), leaves)

    def forward(self, input_ids):
        cfg = self.config
        tensors = [self._parameters[n] for n in self._tree_names]

        def fn(ids, *leaf_vals):
            params = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(init_spec_like(cfg)), list(leaf_vals)
            )
            return forward(cfg, params, ids, remat=False)

        return apply_op("llama_forward", fn, [input_ids] + tensors)


def init_spec_like(cfg: LlamaConfig):
    """Abstract pytree with the same structure as init_params (no allocation)."""
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    s = {
        "embed": 0,
        "final_norm": 0,
        "layers": {
            "input_norm": 0, "post_norm": 0, "wq": 0, "wk": 0, "wv": 0,
            "wo": 0, "w_gate": 0, "w_up": 0, "w_down": 0,
        },
    }
    if not cfg.tie_word_embeddings:
        s["lm_head"] = 0
    return s


class LlamaForCausalLM(LlamaModel):
    def forward(self, input_ids, labels=None):
        logits = super().forward(input_ids)
        if labels is None:
            return logits
        from ..nn import functional as F
        from ..ops.manipulation import reshape

        b, s, v = logits.shape
        loss = F.cross_entropy(reshape(logits, (b * s, v)), reshape(labels, (b * s,)))
        return logits, loss
