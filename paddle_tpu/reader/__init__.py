"""Legacy reader decorators (reference: python/paddle/reader/decorator.py).

Kept for script parity; io.DataLoader is the performant path (device-feeding
with multiprocess shm workers)."""

from __future__ import annotations

import random as _random
from itertools import chain as _chain

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "cache", "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    """Raised when composed readers yield different numbers of samples
    (reference: reader/decorator.py ComposeNotAligned)."""


def map_readers(func, *readers):
    def reader():
        for vals in zip(*[r() for r in readers]):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    def chained():
        return _chain(*[r() for r in readers])
    return chained


def compose(*readers, check_alignment=True):
    from itertools import zip_longest

    _END = object()

    def composed():
        its = [r() for r in readers]
        for items in zip_longest(*its, fillvalue=_END):
            # identity checks: `in`/`==` would broadcast over array samples
            if any(i is _END for i in items):
                if check_alignment and any(i is not _END for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned (different lengths)")
                return  # aligned end, or misalignment tolerated
            out = []
            for it in items:
                out.extend(it if isinstance(it, tuple) else (it,))
            yield tuple(out)
    return composed


def buffered(reader, size):
    # single-controller analog: queue-based readahead is io.DataLoader's job;
    # semantics here are just pass-through ordering
    def buffered_reader():
        yield from reader()
    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item
    return firstn_reader


def cache(reader):
    all_data = []
    filled = []

    def cached():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        yield from all_data
    return cached
