"""paddle.tensor namespace (reference: python/paddle/tensor/ — the functional
tensor library re-exported at the root).  paddle_tpu keeps one implementation
in ops/ and mirrors it here for scripts that import via paddle.tensor.xxx."""

from ..ops.creation import *  # noqa: F401,F403
from ..ops.linalg import *  # noqa: F401,F403
from ..ops.manipulation import *  # noqa: F401,F403
from ..ops.math import *  # noqa: F401,F403
from ..ops.extras import *  # noqa: F401,F403
from ..ops.registry import OPS as _OPS

for _name, _od in list(_OPS.items()):
    if _name not in globals():
        globals()[_name] = _od.fn
del _name, _od, _OPS
