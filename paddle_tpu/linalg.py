"""paddle.linalg namespace (reference: python/paddle/linalg.py — a re-export
of tensor.linalg).  The implementations live in ops/linalg.py (XLA lax.linalg
backends)."""

from .ops.creation import diagonal  # noqa: F401
from .ops.linalg import *  # noqa: F401,F403
from .ops.math import cross  # noqa: F401
