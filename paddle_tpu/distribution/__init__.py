"""Probability distributions (reference: python/paddle/distribution/ —
Distribution base, Normal/Uniform/Categorical/Bernoulli/Beta/Dirichlet/
Multinomial/Gamma/Laplace/LogNormal/Gumbel, TransformedDistribution,
kl_divergence registry at distribution/kl.py).

TPU-native: sampling uses the framework RNG (threefry keys from
paddle_tpu.core.rng, the Generator {seed, offset} semantics of
paddle/phi/core/generator.h); log_prob/entropy are pure jnp and
differentiable through the tape."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import rng as _rng
from ..core.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
    "Beta", "Dirichlet", "Exponential", "Gamma", "Geometric", "Gumbel",
    "Laplace", "LogNormal", "Multinomial", "Poisson", "StudentT",
    "kl_divergence", "register_kl",
]


def _val(x, dtype=jnp.float32):
    v = _unwrap(x)
    return jnp.asarray(v, dtype) if not hasattr(v, "dtype") or v.dtype != dtype else v


def _next_key():
    return _rng.next_key()


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op("dist_prob", jnp.exp, [self.log_prob(value)])

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """Reference: python/paddle/distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(_next_key(), shape, self.loc.dtype)
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            var = self.scale ** 2
            return (-((v - self.loc) ** 2) / (2 * var)
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

        return apply_op("normal_log_prob", fn, [value])

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) +
                      jnp.log(self.scale) * jnp.ones(self.batch_shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.base = Normal(loc, scale)
        super().__init__(self.base.batch_shape)

    def sample(self, shape=(), seed=0):
        return Tensor(jnp.exp(_unwrap(self.base.sample(shape))))

    def log_prob(self, value):
        def fn(v):
            logv = jnp.log(v)
            return _unwrap(self.base.log_prob(Tensor(logv))) - logv

        return apply_op("lognormal_log_prob", fn, [value])

    def entropy(self):
        return Tensor(_unwrap(self.base.entropy()) + self.base.loc)


class Uniform(Distribution):
    """Reference: python/paddle/distribution/uniform.py."""

    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shape, self.low.dtype)
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            inside = (v >= self.low) & (v < self.high)
            lp = -jnp.log(self.high - self.low)
            return jnp.where(inside, lp, -jnp.inf)

        return apply_op("uniform_log_prob", fn, [value])

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low) * jnp.ones(self.batch_shape))


class Categorical(Distribution):
    """Reference: python/paddle/distribution/categorical.py (logits input)."""

    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None:
            self.logits = _val(logits)
        else:
            self.logits = jnp.log(jnp.maximum(_val(probs), 1e-38))
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return Tensor(jax.nn.softmax(self.logits, axis=-1))

    def sample(self, shape=(), seed=0):
        out = jax.random.categorical(_next_key(), self.logits,
                                     shape=tuple(shape) + self.batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        def fn(v):
            logp = jax.nn.log_softmax(self.logits, axis=-1)
            vb = v.astype(jnp.int32)
            b = jnp.broadcast_shapes(logp.shape[:-1], vb.shape)
            logp_b = jnp.broadcast_to(logp, b + logp.shape[-1:])
            vb = jnp.broadcast_to(vb, b)
            return jnp.take_along_axis(logp_b, vb[..., None], axis=-1)[..., 0]

        return apply_op("categorical_log_prob", fn, [value])

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        p = jnp.exp(logp)
        return Tensor(-jnp.sum(p * logp, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self.probs_ = _val(probs)
            self.logits_ = jnp.log(self.probs_ / (1 - self.probs_))
        else:
            self.logits_ = _val(logits)
            self.probs_ = jax.nn.sigmoid(self.logits_)
        super().__init__(self.probs_.shape)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(_next_key(), self.probs_, shape)
                      .astype(jnp.float32))

    def log_prob(self, value):
        def fn(v):
            p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return apply_op("bernoulli_log_prob", fn, [value])

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.beta(_next_key(), self.alpha, self.beta, shape))

    def log_prob(self, value):
        def fn(v):
            from jax.scipy.special import betaln

            return ((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v)
                    - betaln(self.alpha, self.beta))

        return apply_op("beta_log_prob", fn, [value])

    def entropy(self):
        from jax.scipy.special import betaln, digamma

        a, b = self.alpha, self.beta
        return Tensor(betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                      + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(_next_key(), self.concentration, shape))

    def log_prob(self, value):
        def fn(v):
            from jax.scipy.special import gammaln

            a = self.concentration
            return (jnp.sum((a - 1) * jnp.log(v), -1)
                    + gammaln(jnp.sum(a, -1)) - jnp.sum(gammaln(a), -1))

        return apply_op("dirichlet_log_prob", fn, [value])


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.exponential(_next_key(), shape) / self.rate)

    def log_prob(self, value):
        return apply_op("exponential_log_prob",
                        lambda v: jnp.log(self.rate) - self.rate * v, [value])

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _val(concentration)
        self.rate = _val(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.gamma(_next_key(), self.concentration, shape)
                      / self.rate)

    def log_prob(self, value):
        def fn(v):
            from jax.scipy.special import gammaln

            a, r = self.concentration, self.rate
            return a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v - gammaln(a)

        return apply_op("gamma_log_prob", fn, [value])


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _val(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shape)
        return Tensor(jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        return apply_op("geometric_log_prob",
                        lambda v: v * jnp.log1p(-self.probs_) + jnp.log(self.probs_),
                        [value])


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.gumbel(_next_key(), shape))

    def log_prob(self, value):
        def fn(v):
            z = (v - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)

        return apply_op("gumbel_log_prob", fn, [value])

    def entropy(self):
        return Tensor(jnp.log(self.scale) + 1 + jnp.euler_gamma *
                      jnp.ones(self.batch_shape))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.laplace(_next_key(), shape))

    def log_prob(self, value):
        return apply_op(
            "laplace_log_prob",
            lambda v: -jnp.abs(v - self.loc) / self.scale
            - jnp.log(2 * self.scale), [value])

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_ = _val(probs)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    def sample(self, shape=(), seed=0):
        n = self.probs_.shape[-1]
        draws = jax.random.categorical(
            _next_key(), jnp.log(jnp.maximum(self.probs_, 1e-38)),
            shape=tuple(shape) + self.batch_shape + (self.total_count,))
        return Tensor(jax.nn.one_hot(draws, n).sum(-2))

    def log_prob(self, value):
        def fn(v):
            from jax.scipy.special import gammaln

            return (gammaln(self.total_count + 1.0)
                    - jnp.sum(gammaln(v + 1.0), -1)
                    + jnp.sum(v * jnp.log(jnp.maximum(self.probs_, 1e-38)), -1))

        return apply_op("multinomial_log_prob", fn, [value])


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.poisson(_next_key(), self.rate, shape)
                      .astype(jnp.float32))

    def log_prob(self, value):
        def fn(v):
            from jax.scipy.special import gammaln

            return v * jnp.log(self.rate) - self.rate - gammaln(v + 1.0)

        return apply_op("poisson_log_prob", fn, [value])


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _val(df)
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.t(_next_key(), self.df, shape))

    def log_prob(self, value):
        def fn(v):
            from jax.scipy.special import gammaln

            d, z = self.df, (v - self.loc) / self.scale
            return (gammaln((d + 1) / 2) - gammaln(d / 2)
                    - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                    - (d + 1) / 2 * jnp.log1p(z * z / d))

        return apply_op("studentt_log_prob", fn, [value])


# ---- KL registry (reference: python/paddle/distribution/kl.py) ------------

_KL_REGISTRY: dict = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(f"kl_divergence({type(p).__name__}, "
                                  f"{type(q).__name__}) not registered")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_p, var_q = p.scale ** 2, q.scale ** 2
    out = (jnp.log(q.scale / p.scale) + (var_p + (p.loc - q.loc) ** 2) / (2 * var_q)
           - 0.5)
    return Tensor(out)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, -1)
    logq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
    b = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
    return Tensor(a * jnp.log(a / b) + (1 - a) * jnp.log((1 - a) / (1 - b)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


from . import transform  # noqa: E402,F401
from .tail import (  # noqa: E402,F401
    Binomial,
    Cauchy,
    Chi2,
    ContinuousBernoulli,
    ExponentialFamily,
    Independent,
    LKJCholesky,
    MultivariateNormal,
    TransformedDistribution,
)

__all__ += [
    "Binomial", "Cauchy", "Chi2", "ContinuousBernoulli", "ExponentialFamily",
    "Independent", "LKJCholesky", "MultivariateNormal",
    "TransformedDistribution", "transform",
]
