"""Bijective transforms (reference: python/paddle/distribution/transform.py
— Transform taxonomy with forward/inverse/log_det_jacobian, consumed by
TransformedDistribution)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, _unwrap

__all__ = [
    "Transform", "AffineTransform", "ExpTransform", "PowerTransform",
    "AbsTransform", "SigmoidTransform", "TanhTransform", "SoftmaxTransform",
    "ChainTransform", "IndependentTransform", "ReshapeTransform",
    "StackTransform", "StickBreakingTransform",
]


class Transform:
    """Base (transform.py Transform): y = forward(x); log_det is d y / d x."""

    _domain_event_dim = 0

    def forward(self, x):
        return Tensor(self._forward(_unwrap(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_unwrap(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._fldj(_unwrap(x)))

    def inverse_log_det_jacobian(self, y):
        return Tensor(-self._fldj(self._inverse(_unwrap(y))))

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(_unwrap(loc))
        self.scale = jnp.asarray(_unwrap(scale))

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = jnp.asarray(_unwrap(power))

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class AbsTransform(Transform):
    """Non-injective |x| (transform.py AbsTransform); inverse returns the
    positive branch."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        raise NotImplementedError("AbsTransform is not bijective")


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh^2 x) = 2(log 2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _domain_event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError("softmax is not bijective")


class StickBreakingTransform(Transform):
    """R^{K} → simplex^{K+1} (transform.py StickBreakingTransform)."""

    _domain_event_dim = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), axis=-1) + 1
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones_like(z[..., :1])], axis=-1)
        onez = jnp.concatenate([jnp.ones_like(z[..., :1]), 1 - z], axis=-1)
        return zpad * jnp.cumprod(onez, axis=-1)

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], axis=-1)
        offset = y.shape[-1] - 1 - jnp.cumsum(jnp.ones_like(y[..., :-1]),
                                              axis=-1) + 1
        z = y[..., :-1] / (1 - jnp.concatenate(
            [jnp.zeros_like(ycum[..., :1]), ycum[..., :-1]], axis=-1))
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _fldj(self, x):
        # standard identity (1 - sigmoid(t) = exp(-t)·sigmoid(t)):
        # log|det J| = Σ_k [-t_k + logsigmoid(t_k) + log y_k],
        # t = x - log(offset), y = forward(x) head
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), axis=-1) + 1
        t = x - jnp.log(offset)
        y = self._forward(x)
        return jnp.sum(-t + jax.nn.log_sigmoid(t) + jnp.log(y[..., :-1]),
                       axis=-1)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._domain_event_dim = max(
            (t._domain_event_dim for t in self.transforms), default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        # terms must agree on event rank before summing: a per-element
        # [..., K] term from a scalar transform is reduced over the chain's
        # event dims so it aligns with event-reduced [...] terms
        total = 0.0
        for t in self.transforms:
            ldj = t._fldj(x)
            extra = self._domain_event_dim - t._domain_event_dim
            if extra > 0 and jnp.ndim(ldj) >= extra:
                ldj = jnp.sum(ldj, axis=tuple(range(-extra, 0)))
            total = total + ldj
            x = t._forward(x)
        return total


class IndependentTransform(Transform):
    """Reinterpret trailing dims as event dims: log_det sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._domain_event_dim = base._domain_event_dim + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ld = self.base._fldj(x)
        return jnp.sum(ld, axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._domain_event_dim = len(self.in_event_shape)

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _fldj(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch)


class StackTransform(Transform):
    """Apply one transform per slice along ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, x, method):
        parts = [getattr(t, method)(xi) for t, xi in
                 zip(self.transforms, jnp.moveaxis(x, self.axis, 0))]
        return jnp.stack(parts, axis=self.axis)

    def _forward(self, x):
        return self._map(x, "_forward")

    def _inverse(self, y):
        return self._map(y, "_inverse")

    def _fldj(self, x):
        return self._map(x, "_fldj")
