"""Distribution-family tail (reference: python/paddle/distribution/ —
binomial.py, cauchy.py, chi2.py, continuous_bernoulli.py,
exponential_family.py, independent.py, lkj_cholesky.py,
multivariate_normal.py, transformed_distribution.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, gammaln, multigammaln

from ..core.tensor import Tensor, apply_op, _unwrap
from . import Distribution, Gamma, _next_key, _val

__all__ = [
    "ExponentialFamily", "Binomial", "Cauchy", "Chi2", "ContinuousBernoulli",
    "Independent", "LKJCholesky", "MultivariateNormal",
    "TransformedDistribution",
]


class ExponentialFamily(Distribution):
    """Natural-parameter family base (exponential_family.py): subclasses
    provide ``_natural_parameters`` and ``_log_normalizer``; entropy comes
    from the Bregman identity  H = F(θ) - ⟨θ, ∇F(θ)⟩ - E[carrier]."""

    _mean_carrier_measure = 0.0

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    def entropy(self):
        nparams = [jnp.asarray(p) for p in self._natural_parameters]

        def F(*ps):
            return jnp.sum(self._log_normalizer(*ps))

        grads = jax.grad(F, argnums=tuple(range(len(nparams))))(*nparams)
        result = self._log_normalizer(*nparams) - self._mean_carrier_measure
        for p, g in zip(nparams, grads):
            result = result - p * g
        return Tensor(result)


class Binomial(Distribution):
    """binomial.py — counts of successes in ``total_count`` trials."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = jnp.asarray(_unwrap(total_count))
        self.probs = _val(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        n = jnp.broadcast_to(self.total_count, self.batch_shape)
        p = jnp.broadcast_to(self.probs, self.batch_shape)
        return Tensor(jax.random.binomial(
            _next_key(), n.astype(jnp.float32), p, shape).astype(jnp.float32))

    def log_prob(self, value):
        def fn(v):
            n = self.total_count.astype(jnp.float32)
            p = self.probs
            return (gammaln(n + 1) - gammaln(v + 1) - gammaln(n - v + 1)
                    + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

        return apply_op("binomial_log_prob", fn, [value])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs * (1 - self.probs))

    def entropy(self):
        """Exact by support enumeration (the reference kernel enumerates
        too; total_count must be concrete)."""
        n_max = int(jnp.max(self.total_count))
        k = jnp.arange(n_max + 1, dtype=jnp.float32)
        shape = (n_max + 1,) + tuple(1 for _ in self.batch_shape)
        kk = k.reshape(shape)
        n = self.total_count.astype(jnp.float32)
        p = self.probs
        logp = (gammaln(n + 1) - gammaln(kk + 1) - gammaln(n - kk + 1)
                + kk * jnp.log(p) + (n - kk) * jnp.log1p(-p))
        valid = kk <= n
        pmf = jnp.where(valid, jnp.exp(logp), 0.0)
        return Tensor(-jnp.sum(pmf * jnp.where(valid, logp, 0.0), axis=0))


class Cauchy(Distribution):
    """cauchy.py — heavy-tailed, undefined mean/variance."""

    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shape, minval=1e-7, maxval=1 - 1e-7)
        return Tensor(self.loc + self.scale * jnp.tan(math.pi * (u - 0.5)))

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            z = (v - self.loc) / self.scale
            return -jnp.log(math.pi * self.scale * (1 + z * z))

        return apply_op("cauchy_log_prob", fn, [value])

    def cdf(self, value):
        def fn(v):
            return jnp.arctan((v - self.loc) / self.scale) / math.pi + 0.5

        return apply_op("cauchy_cdf", fn, [value])

    def entropy(self):
        return Tensor(jnp.log(4 * math.pi * self.scale)
                      * jnp.ones(self.batch_shape))


class Chi2(Gamma):
    """chi2.py — Gamma(df/2, rate=1/2)."""

    def __init__(self, df, name=None):
        df = _val(df)
        super().__init__(df * 0.5, jnp.full_like(df, 0.5))

    @property
    def df(self):
        return Tensor(self.concentration * 2)


class ContinuousBernoulli(Distribution):
    """continuous_bernoulli.py — [0,1]-supported relaxation with the
    log-normalizer C(λ) = log(2 atanh(1-2λ) / (1-2λ)) (λ ≠ ½)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _val(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _outside(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _log_norm(self):
        p = self.probs
        safe = jnp.where(self._outside(), p, 0.25)
        cut = jnp.log(2 * jnp.arctanh(1 - 2 * safe) / (1 - 2 * safe))
        # Taylor expansion around ½ for the removable singularity
        x = p - 0.5
        taylor = math.log(2.0) + (4.0 / 3) * x * x
        return jnp.where(self._outside(), cut, taylor)

    def log_prob(self, value):
        def fn(v):
            p = self.probs
            return (v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                    + self._log_norm())

        return apply_op("cb_log_prob", fn, [value])

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shape, minval=1e-6, maxval=1 - 1e-6)
        p = self.probs
        safe = jnp.where(self._outside(), p, 0.25)
        # inverse CDF for λ ≠ ½ (continuous_bernoulli.py icdf):
        # F⁻¹(u) = [log1p(-λ + u(2λ-1)) - log1p(-λ)] / [log λ - log1p(-λ)]
        icdf = (jnp.log1p(-safe + u * (2 * safe - 1)) - jnp.log1p(-safe)) \
            / (jnp.log(safe) - jnp.log1p(-safe))
        return Tensor(jnp.where(self._outside(), jnp.clip(icdf, 0, 1), u))

    rsample = sample

    @property
    def mean(self):
        p = self.probs
        safe = jnp.where(self._outside(), p, 0.25)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        x = p - 0.5
        taylor = 0.5 + x / 3.0
        return Tensor(jnp.where(self._outside(), m, taylor))

    def entropy(self):
        def fn(m):
            p = self.probs
            return -(m * jnp.log(p) + (1 - m) * jnp.log1p(-p)
                     + self._log_norm())

        return apply_op("cb_entropy", fn, [self.mean])


class Independent(Distribution):
    """independent.py — reinterpret trailing batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank, name=None):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        if self.rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds base batch rank")
        cut = len(base.batch_shape) - self.rank
        super().__init__(base.batch_shape[:cut],
                         base.batch_shape[cut:] + base.event_shape)

    def sample(self, shape=(), seed=0):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)

        def fn(v):
            return jnp.sum(v, axis=tuple(range(-self.rank, 0)))

        return apply_op("independent_log_prob", fn, [lp])

    def entropy(self):
        def fn(v):
            return jnp.sum(v, axis=tuple(range(-self.rank, 0)))

        return apply_op("independent_entropy", fn, [self.base.entropy()])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance


class MultivariateNormal(Distribution):
    """multivariate_normal.py — parameterized by covariance, precision, or
    scale_tril; sampling and log_prob go through the Cholesky factor (the
    TPU-friendly triangular form)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _val(loc)
        given = [a is not None for a in
                 (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError("pass exactly one of covariance_matrix / "
                             "precision_matrix / scale_tril")
        if scale_tril is not None:
            self._L = jnp.asarray(_unwrap(scale_tril), jnp.float32)
        elif covariance_matrix is not None:
            self._L = jnp.linalg.cholesky(
                jnp.asarray(_unwrap(covariance_matrix), jnp.float32))
        else:
            prec = jnp.asarray(_unwrap(precision_matrix), jnp.float32)
            self._L = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        d = self._L.shape[-1]
        batch = jnp.broadcast_shapes(self.loc.shape[:-1], self._L.shape[:-2])
        super().__init__(batch, (d,))

    @property
    def scale_tril(self):
        return Tensor(self._L)

    @property
    def covariance_matrix(self):
        return Tensor(self._L @ jnp.swapaxes(self._L, -1, -2))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc,
                                       self.batch_shape + self.event_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(jnp.sum(self._L ** 2, axis=-1),
                                       self.batch_shape + self.event_shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(_next_key(), shape, jnp.float32)
        return Tensor(self.loc + jnp.einsum("...ij,...j->...i", self._L, eps))

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            diff = v - self.loc
            # solve L z = diff (triangular): Mahalanobis via z·z
            z = jax.scipy.linalg.solve_triangular(
                self._L, diff[..., None], lower=True)[..., 0]
            d = self._L.shape[-1]
            half_logdet = jnp.sum(jnp.log(
                jnp.diagonal(self._L, axis1=-2, axis2=-1)), -1)
            return (-0.5 * jnp.sum(z * z, -1) - half_logdet
                    - 0.5 * d * math.log(2 * math.pi))

        return apply_op("mvn_log_prob", fn, [value])

    def entropy(self):
        d = self._L.shape[-1]
        half_logdet = jnp.sum(jnp.log(
            jnp.diagonal(self._L, axis1=-2, axis2=-1)), -1)
        return Tensor((0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet)
                      * jnp.ones(self.batch_shape))


class LKJCholesky(Distribution):
    """lkj_cholesky.py — Cholesky factors of correlation matrices, density
    ∝ Π_i L_ii^{dim - i - 1 + 2(η-1)} (row i, 0-indexed), sampled with the
    onion construction."""

    def __init__(self, dim, concentration=1.0, sample_method="onion",
                 name=None):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        self.dim = int(dim)
        self.concentration = _val(concentration)
        self.sample_method = sample_method
        super().__init__(self.concentration.shape, (self.dim, self.dim))

    def sample(self, shape=(), seed=0):
        d = self.dim
        eta = jnp.broadcast_to(self.concentration, self.batch_shape)
        shape = tuple(shape) + self.batch_shape
        rows = [jnp.zeros(shape + (d,)).at[..., 0].set(1.0)]
        for i in range(1, d):
            # onion: y ~ Beta(i/2, η + (d-1-i)/2) is the squared radius of
            # the first i coordinates; direction uniform on S^{i-1}
            b = jax.random.beta(_next_key(), i / 2.0,
                                eta + (d - 1 - i) / 2.0, shape)
            u = jax.random.normal(_next_key(), shape + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            head = jnp.sqrt(b)[..., None] * u
            diag = jnp.sqrt(1.0 - b)[..., None]
            pad = jnp.zeros(shape + (d - i - 1,))
            rows.append(jnp.concatenate([head, diag, pad], axis=-1))
        return Tensor(jnp.stack(rows, axis=-2))

    def log_prob(self, value):
        d = self.dim
        eta = self.concentration

        def fn(L):
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]  # rows 1..d-1
            i = jnp.arange(1, d, dtype=jnp.float32)
            order = d - i - 1 + 2 * (eta[..., None] - 1)
            unnorm = jnp.sum(order * jnp.log(diag), -1)
            # normalizer (lkj_cholesky.py log_normalizer): dm1 = d-1,
            # α = η + dm1/2;  log Z = dm1/2·log π + log Γ_{dm1}(α-½) - dm1·log Γ(α)
            dm1 = d - 1
            alpha = eta + 0.5 * dm1
            log_norm = (0.5 * dm1 * math.log(math.pi)
                        + multigammaln(alpha - 0.5, dm1)
                        - dm1 * gammaln(alpha))
            return unnorm - log_norm

        return apply_op("lkj_log_prob", fn, [value])


class TransformedDistribution(Distribution):
    """transformed_distribution.py — push a base distribution through a
    chain of bijectors; log_prob pulls back through inverses with the
    log-det corrections."""

    def __init__(self, base, transforms, name=None):
        from .transform import ChainTransform, Transform

        if isinstance(transforms, Transform):
            transforms = [transforms]
        if not all(isinstance(t, Transform) for t in transforms):
            raise TypeError("transforms must be Transform instances")
        self.base = base
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)
        # event_shape must describe what sample() RETURNS: shape-changing
        # transforms (Reshape, StickBreaking) alter the trailing dims, so
        # derive the output shape abstractly through the chain
        in_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        try:
            out = jax.eval_shape(self._chain._forward,
                                 jax.ShapeDtypeStruct(in_shape, jnp.float32))
            out_shape = tuple(out.shape)
        except Exception:
            out_shape = in_shape
        nb = len(base.batch_shape)
        super().__init__(out_shape[:nb] if nb else (), out_shape[nb:])

    def sample(self, shape=(), seed=0):
        x = self.base.sample(shape)
        return self._chain.forward(x)

    rsample = sample

    def log_prob(self, value):
        def fn(y):
            event_dim = len(self.base.event_shape)
            lp = 0.0
            for t in reversed(self.transforms):
                x = t._inverse(y)
                ldj = t._fldj(x)
                extra = max(event_dim - t._domain_event_dim, 0)
                if extra and jnp.ndim(ldj) >= extra:
                    ldj = jnp.sum(ldj, axis=tuple(range(-extra, 0)))
                lp = lp - ldj
                y = x
            return lp + _unwrap(self.base.log_prob(Tensor(y)))

        return apply_op("transformed_log_prob", fn, [value])
