"""AMP: auto_cast + GradScaler (reference: python/paddle/amp/ — auto_cast at
auto_cast.py:1006, O1/O2 white/black lists in amp_lists.py, GradScaler in
grad_scaler.py; hooks generated per-op in eager_gen.py:645).

TPU-native realization: bf16 is the native mixed-precision dtype (no loss scaling
needed); ``auto_cast`` installs a dispatch-level dtype policy consulted by the op
wrappers.  fp16 + GradScaler is kept for API parity and exercises the same code
path."""

from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor, _unwrap

__all__ = [
    "auto_cast",
    "amp_guard",
    "GradScaler",
    "decorate",
    "is_bfloat16_supported",
    "is_float16_supported",
    "white_list",
    "black_list",
]

# O1 op lists (mirrors python/paddle/amp/amp_lists.py semantics)
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "flash_attention", "sdpa", "addmm",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "mean", "sum", "softmax",
    "log_softmax", "cross_entropy", "softmax_with_cross_entropy", "layer_norm",
    "batch_norm", "group_norm", "instance_norm", "rms_norm", "norm", "cumsum",
    "pow", "square", "reciprocal", "rsqrt", "erf", "erfinv",
}


def white_list():
    return {"float16": {"O1": WHITE_LIST, "O2": WHITE_LIST}, "bfloat16": {"O1": WHITE_LIST, "O2": WHITE_LIST}}


def black_list():
    return {"float16": {"O1": BLACK_LIST, "O2": BLACK_LIST}, "bfloat16": {"O1": BLACK_LIST, "O2": BLACK_LIST}}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = np.dtype("bfloat16")
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


def _cast_inputs(name: str, vals):
    """Called from op dispatch: cast inputs per the active policy."""
    if not _state.enabled:
        return vals
    target = None
    if name in _state.custom_black or (name in BLACK_LIST and name not in _state.custom_white):
        target = np.dtype("float32")
    elif _state.level == "O2" or name in WHITE_LIST or name in _state.custom_white:
        target = _state.dtype
    if target is None:
        return vals
    out = []
    for v in vals:
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) and v.dtype != target:
            out.append(v.astype(target))
        else:
            out.append(v)
    return out


# register the dispatch-level cast hook
from ..core import tensor as _core_tensor

_core_tensor._amp_cast_hook = _cast_inputs


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1", dtype="bfloat16", use_promote=True):
    prev = (_state.enabled, _state.dtype, _state.level, _state.custom_white, _state.custom_black)
    _state.enabled = bool(enable)
    _state.dtype = dtypes.convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white, _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16", master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the amp dtype (master weights kept by
    the optimizer when multi_precision=True)."""
    dt = dtypes.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if dtypes.is_floating(p.dtype) and np.dtype(p.dtype) == np.float32:
                    p._value = _unwrap(p).astype(dt)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True


class GradScaler:
    """Loss scaler for fp16 (reference: python/paddle/amp/grad_scaler.py).
    bf16 training doesn't need it; kept for parity and fp16 experiments."""

    def __init__(
        self,
        enable=True,
        init_loss_scaling=65536.0,
        incr_ratio=2.0,
        decr_ratio=0.5,
        incr_every_n_steps=2000,
        decr_every_n_nan_or_inf=1,
        use_dynamic_loss_scaling=True,
    ):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        params = optimizer._parameter_list or []
        found = False
        for p in params:
            if p._grad is not None:
                g = p._grad / self._scale
                if bool(jnp.any(~jnp.isfinite(g))):
                    found = True
                p._grad = g
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)  # no-op if the caller already unscaled
        found = self._found_inf
        self._found_inf = False
        self._unscaled = False
        if found:
            self._update_on_inf()
            return
        optimizer.step()
        self._update_on_good()

    def update(self):
        pass

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.unscale_(optimizer)
        self.step(optimizer)

    def _update_on_inf(self):
        if self._dynamic:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0

    def _update_on_good(self):
        if self._dynamic:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


# debugging helpers (reference: python/paddle/amp/debugging.py)
def enable_operator_stats_collection():
    pass


def disable_operator_stats_collection():
    pass


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    v = _unwrap(tensor)
    has_inf = bool(jnp.any(jnp.isinf(v)))
    has_nan = bool(jnp.any(jnp.isnan(v)))
    return has_inf, has_nan
