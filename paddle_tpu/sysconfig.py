"""Build/config paths (reference: python/paddle/sysconfig.py)."""

import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory of native headers shipped with the package."""
    return os.path.join(_PKG, "native", "src")


def get_lib() -> str:
    """Directory containing libpaddle_tpu_native.so."""
    return os.path.join(_PKG, "native")
