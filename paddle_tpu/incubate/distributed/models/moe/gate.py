"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/ —
naive_gate.py, gshard_gate.py, switch_gate.py over BaseGate).

Each gate maps token features [T, d] -> (topk_value [T, k], topk_idx [T, k])
and stashes its load-balancing auxiliary loss on ``self.loss`` (the reference
collects it via get_loss on backward)."""

from __future__ import annotations

import jax.numpy as jnp  # noqa: F401

from .....core.tensor import apply_op
from .....nn import functional as F
from .....nn.layer_base import Layer
from .....ops import manipulation as _manip


class BaseGate(Layer):
    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss


class NaiveGate(BaseGate):
    """Plain top-k softmax gate, no aux loss (naive_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        from .....nn.common import Linear

        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores=False):
        gate_logits = self.gate(inp)
        gate_val, gate_idx = _manip.topk(gate_logits, self.top_k, axis=-1)
        gate_val = F.softmax(gate_val, axis=-1)
        if return_all_scores:
            return gate_val, gate_idx, gate_logits
        return gate_val, gate_idx


class GShardGate(BaseGate):
    """Top-2 gate with capacity + load-balance loss (gshard_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2, capacity=(1.2, 2.4), group=None):
        super().__init__(num_expert, world_size)
        from .....nn.common import Linear

        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk
        self.capacity = capacity

    def forward(self, x):
        logits = self.gate(x)
        probs = F.softmax(logits, axis=-1)
        gate_val, gate_idx = _manip.topk(probs, self.top_k, axis=-1)

        n = self.tot_expert

        def aux(p, idx):
            me = jnp.mean(p, axis=0)
            oh = jnp.zeros((idx.shape[0], n), p.dtype).at[
                jnp.arange(idx.shape[0]), idx[:, 0]
            ].set(1.0)
            ce = jnp.mean(oh, axis=0)
            return jnp.sum(me * ce) * n

        self.loss = apply_op("gshard_aux_loss", aux, [probs, gate_idx])
        return gate_val, gate_idx


class SwitchGate(BaseGate):
    """Top-1 switch-transformer gate with capacity + aux loss (switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1, capacity=(1.2, 2.4), group=None):
        super().__init__(num_expert, world_size)
        from .....nn.common import Linear

        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = 1
        self.capacity = capacity

    def forward(self, x):
        logits = self.gate(x)
        probs = F.softmax(logits, axis=-1)
        gate_val, gate_idx = _manip.topk(probs, 1, axis=-1)

        n = self.tot_expert

        def aux(p, idx):
            oh = jnp.zeros((idx.shape[0], n), p.dtype).at[
                jnp.arange(idx.shape[0]), idx[:, 0]
            ].set(1.0)
            freq = jnp.mean(oh, axis=0)
            pmean = jnp.mean(p, axis=0)
            return jnp.sum(freq * pmean) * n

        self.loss = apply_op("switch_aux_loss", aux, [probs, gate_idx])
        return gate_val, gate_idx
