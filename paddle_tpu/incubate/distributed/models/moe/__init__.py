"""Mixture-of-Experts layer with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py
(``MoELayer``), gates in moe/gate/, grad clip in moe/grad_clip.py
(``ClipGradForMOEByGlobalNorm``); dispatch/combine collectives
``global_scatter``/``global_gather`` (python/paddle/distributed/utils/
moe_utils.py).

TPU-native design: dispatch/combine are *dense einsum routing* (the GShard
formulation) instead of variable-size scatter RPCs — a [tokens, experts,
capacity] one-hot dispatch mask and a same-shape combine weight tensor.  Dense
routing is static-shaped (jit-stable), MXU-friendly, and under a mesh the
``expert`` axis sharding turns the einsums into the exact all-to-alls the
reference launches by hand.  Capacity enforcement = position-in-expert cumsum,
matching the reference's ``prune_gate_by_capacity``."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor, _unwrap, apply_op
from .....nn.layer_base import Layer
from .....nn.container import LayerList
from .....ops import creation as _creation, manipulation as _manip, math as _math
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = [
    "MoELayer",
    "NaiveGate",
    "GShardGate",
    "SwitchGate",
    "BaseGate",
    "dispatch_combine_weights",
    "ClipGradForMOEByGlobalNorm",
]


def dispatch_combine_weights(probs_val, topk_idx_val, capacity):
    """Pure: build (combine [T,E,C], dispatch [T,E,C]) from gate probs and
    top-k indices with capacity pruning.  Tokens overflowing an expert's
    capacity are dropped (GShard drop policy)."""
    T, E = probs_val.shape
    k = topk_idx_val.shape[1]
    C = int(capacity)

    combine = jnp.zeros((T, E, C), probs_val.dtype)
    # token's slot within each expert, computed sequentially over the k choices
    # so first-choice tokens claim capacity first (reference ordering)
    expert_fill = jnp.zeros((E,), jnp.int32)
    for j in range(k):
        idx = topk_idx_val[:, j]  # [T]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)  # [T, E]
        pos_in_expert = jnp.cumsum(onehot, axis=0) - 1 + expert_fill[None, :]  # [T, E]
        pos = jnp.take_along_axis(pos_in_expert, idx[:, None], axis=1)[:, 0]  # [T]
        keep = pos < C
        gate_w = jnp.take_along_axis(probs_val, idx[:, None], axis=1)[:, 0]
        w = jnp.where(keep, gate_w, 0.0)
        slot = jnp.clip(pos, 0, C - 1)
        combine = combine.at[jnp.arange(T), idx, slot].add(w)
        expert_fill = expert_fill + jnp.sum(onehot, axis=0)
    dispatch = (combine > 0).astype(probs_val.dtype)
    return combine, dispatch


class MoELayer(Layer):
    """``MoELayer(d_model, experts, gate="gshard", moe_group=..., ...)``.

    experts: LayerList (or list) of expert networks [num_local_experts].
    gate: "naive"|"gshard"|"switch", a dict {"type": ...}, or a BaseGate."""

    def __init__(
        self,
        d_model,
        experts=None,
        gate=None,
        moe_group=None,
        mp_group=None,
        recompute_interval=0,
        top_k=2,
        capacity_factor=None,
        **kwargs,
    ):
        super().__init__()
        self.d_model = d_model
        if experts is None:
            raise ValueError("MoELayer requires an `experts` list/LayerList")
        if isinstance(experts, (list, tuple)):
            experts = LayerList(list(experts))
        self.experts = experts
        self.world_size = getattr(moe_group, "nranks", 1) if moe_group is not None else 1
        # single-controller: `experts` is the GLOBAL expert list (the reference
        # holds num_expert local experts per rank; here all world_size*num_expert
        # are visible, each with distinct weights)
        if len(experts) % self.world_size:
            raise ValueError(
                f"len(experts)={len(experts)} must divide by moe_group.nranks={self.world_size}"
            )
        self.num_expert = len(experts) // self.world_size
        self.moe_group = moe_group
        self.recompute_interval = recompute_interval
        self.capacity_factor = capacity_factor

        if isinstance(gate, BaseGate):
            self.gate = gate
        else:
            gate_type = gate.get("type", "gshard") if isinstance(gate, dict) else (gate or "gshard")
            top_k = gate.get("top_k", top_k) if isinstance(gate, dict) else top_k
            if gate_type == "naive":
                self.gate = NaiveGate(d_model, self.num_expert, self.world_size, topk=top_k)
            elif gate_type == "gshard":
                self.gate = GShardGate(d_model, self.num_expert, self.world_size, topk=top_k)
            elif gate_type == "switch":
                self.gate = SwitchGate(d_model, self.num_expert, self.world_size)
            else:
                raise ValueError(f"unknown gate type {gate_type!r}")

    @property
    def l_aux(self):
        return self.gate.loss

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        xf = _manip.reshape(x, [-1, d])
        T = xf.shape[0]
        E = self.num_expert * self.world_size
        # an explicitly-passed layer capacity_factor wins; otherwise use the
        # gate's (train, eval) capacity tuple, else the 1.2 GShard default
        # (reference gshard_gate.py/switch_gate.py capacity semantics)
        cap_factor = self.capacity_factor
        gate_cap = getattr(self.gate, "capacity", None)
        if cap_factor is None:
            if isinstance(gate_cap, (tuple, list)) and len(gate_cap) == 2:
                cap_factor = gate_cap[0] if self.training else gate_cap[1]
            else:
                cap_factor = 1.2
        capacity = max(1, int(cap_factor * T / E) * getattr(self.gate, "top_k", 2))

        gate_val, gate_idx = self.gate(xf)
        # probs over all experts for combine weights
        # (gate_val is already softmaxed top-k; rebuild a full prob view)
        probs = _creation.zeros([T, E], dtype=xf.dtype)

        def scatter_probs(p, idx, val):
            return p.at[jnp.arange(idx.shape[0])[:, None], idx].set(val)

        probs = apply_op("moe_scatter_probs", scatter_probs, [probs, gate_idx, gate_val])

        def build(p, idx):
            return dispatch_combine_weights(p, idx, capacity)

        combine, dispatch = apply_op("moe_dispatch_weights", build, [probs, gate_idx], n_outputs=2)

        # route: [T,E,C] x [T,d] -> [E,C,d]
        expert_in = _math.einsum("tec,td->ecd", dispatch, xf)

        # run experts (recompute_interval>0 wraps each in activation ckpt);
        # experts is the global list — one distinct network per global expert
        outs = []
        for e in range(E):
            ein = expert_in[e]
            if self.recompute_interval > 0:
                from .....distributed.fleet.recompute import recompute as _rc
                eo = _rc(self.experts[e], ein)
            else:
                eo = self.experts[e](ein)
            outs.append(eo)
        expert_out = _manip.stack(outs, axis=0)  # [E, C, d]

        y = _math.einsum("ecd,tec->td", expert_out, combine)
        return _manip.reshape(y, list(orig_shape))


class ClipGradForMOEByGlobalNorm:
    """Global-norm clip aware of expert params (reference moe/grad_clip.py):
    expert-param grad norms are summed across the moe group before combining
    with the shared-param norm.  Single-controller: expert params are fully
    visible, so the combined norm is exact; `is_expert_param_func` filters."""

    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None):
        self.clip_norm = float(clip_norm)
        self.is_expert = is_expert_param_func or (lambda p: False)
        self.moe_group = moe_group

    def __call__(self, params_grads):
        shared_sq = jnp.zeros((), jnp.float32)
        expert_sq = jnp.zeros((), jnp.float32)
        vals = []
        for p, g in params_grads:
            if g is None:
                continue
            gv = _unwrap(g).astype(jnp.float32)
            if self.is_expert(p):
                # reference allreduces this term over moe_group; the
                # single-controller view already sums every expert's norm
                expert_sq = expert_sq + jnp.sum(gv * gv)
            else:
                shared_sq = shared_sq + jnp.sum(gv * gv)
            vals.append((p, g))
        global_norm = jnp.sqrt(shared_sq + expert_sq)
        scale = jnp.minimum(1.0, self.clip_norm / (global_norm + 1e-6))
        out = []
        for p, g in vals:
            out.append((p, Tensor(_unwrap(g) * scale.astype(_unwrap(g).dtype))))
        return out
