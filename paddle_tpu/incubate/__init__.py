"""incubate: experimental / fused-op surface (reference: python/paddle/incubate/)."""

from . import nn  # noqa: F401
