"""incubate: experimental / fused-op surface (reference: python/paddle/incubate/)."""

from . import asp  # noqa: F401
from . import nn  # noqa: F401
from . import operators  # noqa: F401
from . import optimizer  # noqa: F401
from .operators import (  # noqa: F401
    graph_khop_sampler,
    graph_reindex,
    graph_sample_neighbors,
    graph_send_recv,
    identity_loss,
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
from .optimizer import LookAhead, ModelAverage  # noqa: F401

# `paddle.incubate.inference` in the reference is the paddle-inference
# wrapper namespace; here it aliases the deployable-artifact engine
from .. import inference  # noqa: F401

__all__ = [
    "asp", "nn", "operators", "optimizer", "inference",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "graph_send_recv", "graph_khop_sampler", "graph_reindex",
    "graph_sample_neighbors", "identity_loss", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "LookAhead", "ModelAverage",
]
