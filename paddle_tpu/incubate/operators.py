"""Incubate operator tail (reference: python/paddle/incubate/operators/ —
graph_send_recv.py, graph_khop_sampler.py, graph_reindex.py,
graph_sample_neighbors.py, softmax_mask_fuse.py; incubate/nn/loss.py).

The segment/message-passing math lives in paddle_tpu.geometric (the modern
home); these are the legacy incubate entry points over the same kernels.
Graph SAMPLING is host-side numpy — it is data-dependent-shape control
logic feeding the input pipeline, exactly the part that should NOT be on
the TPU."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, _unwrap
from ..geometric import (  # noqa: F401  (re-exported, reference aliases)
    reindex_graph,
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
    send_u_recv,
)

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "graph_send_recv", "graph_reindex", "graph_sample_neighbors",
    "graph_khop_sampler", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "identity_loss",
]


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """graph_send_recv.py:46 — legacy name for geometric.send_u_recv."""
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """graph_reindex.py:35 — legacy name for geometric.reindex_graph (the
    hashtable buffers are a CUDA optimization; ignored here)."""
    return reindex_graph(x, neighbors, count)


def _csc_neighbors(row, colptr, node):
    return row[colptr[node]:colptr[node + 1]]


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """graph_sample_neighbors.py:77 — uniform neighbor sampling on a CSC
    graph; returns (out_neighbors, out_count[, out_eids])."""
    rowv = np.asarray(_unwrap(row)).reshape(-1)
    cp = np.asarray(_unwrap(colptr)).reshape(-1)
    nodes = np.asarray(_unwrap(input_nodes)).reshape(-1)
    eidsv = None if eids is None else np.asarray(_unwrap(eids)).reshape(-1)
    out_n, out_c, out_e = [], [], []
    for n in nodes:
        lo, hi = int(cp[n]), int(cp[n + 1])
        deg = hi - lo
        if sample_size < 0 or sample_size >= deg:
            picked = np.arange(lo, hi)
        else:
            picked = lo + np.random.choice(deg, sample_size, replace=False)
        out_n.append(rowv[picked])
        out_c.append(len(picked))
        if eidsv is not None:
            out_e.append(eidsv[picked])
    neigh = Tensor(np.concatenate(out_n) if out_n else np.zeros(0, rowv.dtype))
    count = Tensor(np.asarray(out_c, np.int32))
    if return_eids:
        if eidsv is None:
            raise ValueError("return_eids=True requires eids")
        return neigh, count, Tensor(np.concatenate(out_e)
                                    if out_e else np.zeros(0, eidsv.dtype))
    return neigh, count


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """graph_khop_sampler.py:63 — multi-layer sampling + subgraph reindex;
    returns (edge_src, edge_dst, sample_index, reindex_nodes[, edge_eids])."""
    nodes = np.asarray(_unwrap(input_nodes)).reshape(-1)
    frontier = nodes
    all_src, all_dst, all_eids = [], [], []
    for size in sample_sizes:
        if return_eids:
            neigh, count, e = graph_sample_neighbors(
                row, colptr, Tensor(frontier), eids=sorted_eids,
                sample_size=size, return_eids=True)
            all_eids.append(np.asarray(_unwrap(e)))
        else:
            neigh, count = graph_sample_neighbors(
                row, colptr, Tensor(frontier), sample_size=size)
        neigh = np.asarray(_unwrap(neigh))
        count = np.asarray(_unwrap(count))
        dst = np.repeat(frontier, count)
        all_src.append(neigh)
        all_dst.append(dst)
        frontier = np.unique(neigh)
    src = np.concatenate(all_src) if all_src else np.zeros(0, np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.zeros(0, np.int64)
    # subgraph reindex: input nodes first, then newly-seen nodes in order
    order = {int(n): i for i, n in enumerate(nodes)}
    for n in np.concatenate([src, dst]):
        if int(n) not in order:
            order[int(n)] = len(order)
    remap = np.vectorize(lambda n: order[int(n)])
    edge_src = remap(src) if src.size else src
    edge_dst = remap(dst) if dst.size else dst
    sample_index = np.asarray(sorted(order, key=order.get), np.int64)
    reindex_nodes = remap(nodes) if nodes.size else nodes
    outs = (Tensor(np.asarray(edge_src, np.int64)),
            Tensor(np.asarray(edge_dst, np.int64)),
            Tensor(sample_index),
            Tensor(np.asarray(reindex_nodes, np.int64)))
    if return_eids:
        return outs + (Tensor(np.concatenate(all_eids)),)
    return outs


def softmax_mask_fuse(x, mask, name=None):
    """softmax_mask_fuse.py:26 — softmax(x + mask); XLA fuses the add into
    the reduction, which is the entire point of the CUDA kernel."""
    def fn(v, m):
        return jax.nn.softmax((v + m).astype(jnp.float32), axis=-1).astype(v.dtype)

    return apply_op("softmax_mask_fuse", fn, [x, mask])


def softmax_mask_fuse_upper_triangle(x):
    """softmax_mask_fuse_upper_triangle — causal-masked softmax (mask the
    upper triangle above the diagonal) without materializing the mask."""
    def fn(v):
        sq, sk = v.shape[-2], v.shape[-1]
        tri = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(tri, v, -jnp.inf)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(v.dtype)

    return apply_op("softmax_mask_fuse_upper_triangle", fn, [x])


def identity_loss(x, reduction="none"):
    """incubate/nn/loss.py:36 — mark/reduce a loss head."""
    if isinstance(reduction, int):
        reduction = {0: "sum", 1: "mean", 2: "none"}.get(reduction, "none")

    def fn(v):
        if reduction == "mean":
            return jnp.mean(v)
        if reduction == "sum":
            return jnp.sum(v)
        return v

    return apply_op("identity_loss", fn, [x])
