"""ASP — automatic 2:4 structured sparsity (reference:
python/paddle/incubate/asp/ — prune_model/decorate in asp.py, mask
generation utils in utils.py supporting_sparse_2_4 patterns).

TPU note: Ampere's sparse tensor cores have no TPU analog; the MXU runs
dense.  The *workflow* is still valuable (train-dense → prune 2:4 →
fine-tune with frozen masks → deploy pruned weights), so this module keeps
the reference API: masks are computed per weight, applied multiplicatively,
and re-applied after each optimizer step by the decorated optimizer."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, _unwrap
from ...nn.layer_base import Layer

__all__ = [
    "calculate_density", "create_mask", "check_mask_2d", "prune_model",
    "decorate", "reset_excluded_layers", "set_excluded_layers",
]

# masks live on the parameter object itself (attribute `_asp_mask`) so they
# follow the parameter's lifetime — no global registry to leak or collide
_EXCLUDED: set[str] = set()


def calculate_density(x) -> float:
    arr = np.asarray(_unwrap(x))
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def create_mask(weight, func_name="mask_2d_best", n=2, m=4):
    """n:m mask along the last axis: keep the n largest-|w| of every m.
    Requires shape[-1] % m == 0 so groups never straddle rows."""
    arr = np.asarray(_unwrap(weight), np.float32)
    orig = arr.shape
    if orig[-1] % m:
        return np.ones(orig, np.float32)  # not divisible: leave dense
    flat = np.abs(arr).reshape(-1, m)
    keep = np.argsort(-flat, axis=1)[:, :n]
    mask = np.zeros_like(flat)
    np.put_along_axis(mask, keep, 1.0, axis=1)
    return mask.reshape(orig)


def check_mask_2d(mat, n=2, m=4) -> bool:
    arr = np.asarray(_unwrap(mat))
    if arr.shape[-1] % m:
        return False
    groups = (np.abs(arr.reshape(-1, m)) > 0).sum(axis=1)
    return bool(np.all(groups <= n))


def set_excluded_layers(param_names, main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def _prunable(name, param, m=4):
    v = _unwrap(param)
    return (name not in _EXCLUDED and getattr(v, "ndim", 0) >= 2
            and v.shape[-1] % m == 0)


def prune_model(model: Layer, n=2, m=4, mask_algo="mask_2d_best",
                with_mask=True):
    """Apply 2:4 masks to every prunable weight in place; masks are recorded
    so a decorated optimizer keeps enforcing them (reference asp.py:
    prune_model)."""
    pruned = {}
    for name, param in model.named_parameters():
        if not _prunable(name, param, m):
            continue
        mask = create_mask(param, mask_algo, n, m)
        param._value = (_unwrap(param) * jnp.asarray(mask, _unwrap(param).dtype))
        param._asp_mask = jnp.asarray(mask)
        pruned[name] = float(mask.mean())
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step to re-apply the recorded masks after each update
    (reference asp.py:decorate → OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step(*args, **kwargs):
        out = orig_step(*args, **kwargs)
        for p in optimizer._parameter_list or []:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._value = _unwrap(p) * mask.astype(_unwrap(p).dtype)
        return out

    optimizer.step = step
    return optimizer
