"""Fused LLM ops (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm.py, fused_rotary_position_embedding.py, swiglu.py, fused_moe.py,
block_multihead_attention.py, masked_multihead_attention.py).

Each wrapper dispatches through the eager tape to the Pallas/fused-XLA
implementation in paddle_tpu.ops.pallas."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor, _unwrap, apply_op
from ....ops.pallas import rms_norm as _rms
from ....ops.pallas import rope as _rope
from ....ops.pallas import swiglu as _swiglu_mod

__all__ = [
    "fused_rms_norm",
    "fused_layer_norm",
    "fused_rotary_position_embedding",
    "swiglu",
    "fused_linear",
    "fused_bias_act",
    "variable_length_memory_efficient_attention",
    "fused_multi_head_attention",
    "masked_multihead_attention",
    "block_multihead_attention",
    "fused_multi_transformer",
    "fused_matmul_bias",
    "fused_dropout_add",
    "fused_dot_product_attention",
    "fused_gate_attention",
    "blha_get_max_len",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   bias=None, residual=None, quant_scale=-1, **kw):
    inputs = [x, norm_weight]
    has_res = residual is not None
    has_bias = bias is not None
    if has_bias:
        inputs.append(bias)
    if has_res:
        inputs.append(residual)

    def fn(v, w, *rest):
        i = 0
        if has_bias:
            v = v + rest[i]
            i += 1
        if has_res:
            v = v + rest[i]
        out = _rms.rms_norm(v, w, epsilon)
        if norm_bias is not None:
            out = out + _unwrap(norm_bias)
        return (out, v) if has_res else out

    return apply_op("rms_norm", fn, inputs)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, begin_norm_axis=1, bias=None, residual=None, **kw):
    from ....nn import functional as F

    if residual is not None:
        x = x + residual
    if bias is not None:
        x = x + bias
    d = x.shape[begin_norm_axis:] if begin_norm_axis >= 0 else x.shape[-1:]
    out = F.layer_norm(x, d, norm_weight, norm_bias, epsilon)
    return (out, x) if residual is not None else out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None, position_ids=None, use_neox_rotary_style=True):
    inputs = [q]
    mask = [True, k is not None, v is not None, sin is not None, cos is not None, position_ids is not None]
    for t in (k, v, sin, cos, position_ids):
        if t is not None:
            inputs.append(t)

    def fn(*vals):
        it = iter(vals)
        qv = next(it)
        kv = next(it) if mask[1] else None
        vv = next(it) if mask[2] else None
        sn = next(it) if mask[3] else None
        cs = next(it) if mask[4] else None
        pid = next(it) if mask[5] else None
        outs = _rope.fused_rotary_position_embedding(
            qv, kv, vv, sin=sn, cos=cs, position_ids=pid, use_neox_rotary_style=use_neox_rotary_style
        )
        return tuple(o for o in outs if o is not None)

    res = apply_op("fused_rope", fn, inputs)
    res = res if isinstance(res, tuple) else (res,)
    out = []
    it = iter(res)
    for present in mask[:3]:
        out.append(next(it) if present else None)
    return tuple(out)


def swiglu(x, y=None, name=None):
    if y is None:
        def fn(v):
            a, b = jnp.split(v, 2, axis=-1)
            return _swiglu_mod.swiglu(a, b)

        return apply_op("swiglu", fn, [x])
    return apply_op("swiglu", _swiglu_mod.swiglu, [x, y])


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    import jax

    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu, "swiglu": None}
    if act_method == "swiglu":
        if bias is not None:
            x = x + bias
        return swiglu(x)

    def fn(v, *rest):
        if rest:
            v = v + rest[0]
        return acts[act_method](v)

    inputs = [x] + ([bias] if bias is not None else [])
    return apply_op("fused_bias_act", fn, inputs)


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None, kv_seq_lens=None, mask=None, scale=None, causal=False):
    """Reference: python/paddle/incubate/nn/functional/variable_length_memory_efficient_attention.py.
    Inputs are BHSD here (paddle's var-len op convention).  ``kv_seq_lens``
    (default ``seq_lens``) masks each batch row's keys past its true length —
    the variable-length semantics the op exists for."""
    import math

    from ....nn import functional as F
    from ....ops import manipulation as M

    q = M.transpose(query, [0, 2, 1, 3])
    k = M.transpose(key, [0, 2, 1, 3])
    v = M.transpose(value, [0, 2, 1, 3])
    if scale is not None:
        # sdpa divides by sqrt(d); pre-scale q so the effective scale is ours
        hd = int(_unwrap(query).shape[-1])
        q = q * float(scale) * math.sqrt(hd)
    lens = kv_seq_lens if kv_seq_lens is not None else seq_lens
    if lens is not None:
        lv = jnp.asarray(_unwrap(lens)).reshape(-1)          # [B]
        s_kv = int(_unwrap(key).shape[2])                    # BHSD input
        keymask = jnp.arange(s_kv)[None, :] < lv[:, None]    # [B, S_kv]
        km4 = keymask[:, None, None, :]
        if mask is None:
            mv = jnp.where(km4, 0.0, -jnp.inf).astype(jnp.float32)
        else:
            mv = jnp.asarray(_unwrap(mask))
            if mv.dtype == jnp.bool_:
                # bool masks keep True=attend semantics: AND, don't add
                mv = mv & km4
            else:
                mv = (mv + jnp.where(km4, 0.0, -jnp.inf)).astype(jnp.float32)
        mask = Tensor(mv)
    out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask, is_causal=causal)
    return M.transpose(out, [0, 2, 1, 3])


def fused_multi_head_attention(
    x, qkv_weight, linear_weight, pre_layer_norm=False, pre_ln_scale=None,
    pre_ln_bias=None, ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
    qkv_bias=None, linear_bias=None, cache_kv=None, attn_mask=None,
    dropout_rate=0.5, attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
    mode="upscale_in_train", ring_id=-1, add_residual=True,
    transpose_qkv_wb=False, num_heads=-1, name=None):
    """Fused transformer attention block (reference:
    python/paddle/incubate/nn/functional/fused_transformer.py
    fused_multi_head_attention over fused_attention_op.cu; fused_ops.yaml).

    out = [post_ln](residual + dropout(linear(flash_attn(qkv(pre_ln(x))))))

    TPU-native: one dispatch through the Pallas flash-attention kernel — the
    additive/bool ``attn_mask`` streams through the kernel tile-by-tile, so
    the fusion the reference does in CUDA happens in Mosaic/XLA here.
    ``qkv_weight``: [3, num_heads, head_dim, embed_dim] (paddle layout), or
    [embed_dim, 3*embed_dim] with ``transpose_qkv_wb=True`` and ``num_heads``.
    ``cache_kv`` [2, b, nh, s_cache, hd] (decode): current k/v are appended
    and attention runs over the full prefix; returns (out, new_cache_kv).
    Dropout uses the framework RNG and honors ``mode`` like
    nn.functional.dropout."""
    import jax
    import jax.numpy as jnp

    from ....core import rng as _rng
    from ....ops.pallas import flash_attention as _fa

    if transpose_qkv_wb and num_heads <= 0:
        raise ValueError(
            "fused_multi_head_attention: transpose_qkv_wb=True requires "
            f"num_heads > 0 (got {num_heads})")
    drop_key = _rng.next_key() if (training and dropout_rate > 0) else None
    attn_drop_key = _rng.next_key() if (training and attn_dropout_rate > 0) else None

    def _drop(v, key, rate):
        """nn.functional.dropout semantics incl. ``mode``."""
        if rate == 0.0:
            return v
        if key is None:  # eval
            if mode == "downscale_in_infer":
                return (v * (1.0 - rate)).astype(v.dtype)
            return v
        keep = jax.random.bernoulli(key, 1.0 - rate, v.shape)
        if mode == "downscale_in_infer":
            return jnp.where(keep, v, 0.0).astype(v.dtype)
        return jnp.where(keep, v / (1.0 - rate), 0.0).astype(v.dtype)

    opt = [("pls", pre_ln_scale), ("plb", pre_ln_bias), ("lns", ln_scale),
           ("lnb", ln_bias), ("qb", qkv_bias), ("lb", linear_bias),
           ("am", attn_mask), ("ckv", cache_kv)]
    present = [t for _, t in opt if t is not None]
    flags = {n: t is not None for n, t in opt}

    def fn(xv, qkvw, lw, *rest):
        it = iter(rest)
        g = {n: (next(it) if flags[n] else None) for n, _ in opt}

        def ln(v, scale_, bias_, eps):
            mu = v.mean(-1, keepdims=True)
            var = ((v - mu) ** 2).mean(-1, keepdims=True)
            o = (v - mu) * jax.lax.rsqrt(var + eps)
            if scale_ is not None:
                o = o * scale_
            if bias_ is not None:
                o = o + bias_
            return o

        h = ln(xv, g["pls"], g["plb"], pre_ln_epsilon) if pre_layer_norm else xv
        b, s, e = h.shape
        if transpose_qkv_wb:
            nh = num_heads
            hd = e // nh
            qkv = (h @ qkvw).reshape(b, s, 3, nh, hd)
            if g["qb"] is not None:
                qkv = qkv + g["qb"].reshape(3, nh, hd)
        else:
            nh, hd = qkvw.shape[1], qkvw.shape[2]
            qkv = jnp.einsum("bse,thde->bsthd", h, qkvw)
            if g["qb"] is not None:
                qkv = qkv + g["qb"]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b, s, nh, hd]

        new_cache = None
        if g["ckv"] is not None:
            # decode: prepend cached k/v ([2, b, nh, S, hd] BHSD layout)
            k_bhsd = jnp.concatenate(
                [g["ckv"][0], k.transpose(0, 2, 1, 3)], axis=2)
            v_bhsd = jnp.concatenate(
                [g["ckv"][1], v.transpose(0, 2, 1, 3)], axis=2)
            new_cache = jnp.stack([k_bhsd, v_bhsd])
            k = k_bhsd.transpose(0, 2, 1, 3)
            v = v_bhsd.transpose(0, 2, 1, 3)

        if attn_drop_key is None:
            attn = _fa.flash_attention_bshd(q, k, v, attn_mask=g["am"])
        else:
            # attention-probability dropout forces the composed path (the
            # reference's fused op also materializes probs when dropping)
            logits = jnp.einsum("bsnd,bSnd->bnsS", q.astype(jnp.float32),
                                k.astype(jnp.float32)) / jnp.sqrt(
                                    jnp.asarray(hd, jnp.float32))
            if g["am"] is not None:
                m = g["am"]
                logits = jnp.where(m, logits, -1e30) if m.dtype == jnp.bool_ \
                    else logits + m.astype(jnp.float32)
            p = jax.nn.softmax(logits, axis=-1)
            keep = jax.random.bernoulli(attn_drop_key, 1.0 - attn_dropout_rate,
                                        p.shape)
            p = jnp.where(keep, p / (1.0 - attn_dropout_rate), 0.0)
            attn = jnp.einsum("bnsS,bSnd->bsnd", p.astype(v.dtype), v)

        out = attn.reshape(b, s, nh * hd) @ lw
        if g["lb"] is not None:
            out = out + g["lb"]
        out = _drop(out, drop_key, dropout_rate)
        if add_residual:
            out = xv + out
        if not pre_layer_norm:
            out = ln(out, g["lns"], g["lnb"], ln_epsilon)
        out = out.astype(xv.dtype)
        if new_cache is not None:
            return out, new_cache
        return out

    inputs = [x, qkv_weight, linear_weight] + present
    if cache_kv is not None:
        return apply_op("fused_multi_head_attention", fn, inputs, n_outputs=2)
    return apply_op("fused_multi_head_attention", fn, inputs)


def fused_moe(
    x,
    gate_weight,
    ffn1_weight,
    ffn2_weight,
    ffn1_bias=None,
    ffn2_bias=None,
    gate_bias=None,
    moe_topk=2,
    norm_topk_prob=True,
    group_moe=False,
):
    """Fused mixture-of-experts FFN (reference:
    python/paddle/incubate/nn/functional/fused_moe.py over the fused_moe_kernel).

    Dense GShard-style routing: one-hot dispatch einsums feed a single batched
    [E, ...] expert GEMM pair — the layout XLA tiles onto the MXU; under an
    'expert'-sharded mesh GSPMD inserts the all-to-alls the CUDA kernel does by
    hand.  ffn1_weight [E, d, 2h or h], ffn2_weight [E, h, d]."""
    import jax
    import jax.numpy as jnp

    def fn(xv, gw, w1, w2, *rest):
        it = iter(rest)
        b1 = next(it) if ffn1_bias is not None else None
        b2 = next(it) if ffn2_bias is not None else None
        gb = next(it) if gate_bias is not None else None
        orig = xv.shape
        d = orig[-1]
        t = xv.reshape(-1, d)
        logits = t @ gw + (gb if gb is not None else 0.0)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
        E = gw.shape[-1]
        # scatter normalized top-k back to a full [T, E] combine matrix
        full = jnp.zeros((t.shape[0], E), jnp.float32)
        full = full.at[jnp.arange(t.shape[0])[:, None], topi].set(topv)
        # batched expert FFN on all tokens (dense; capacity-free == no drops)
        h = jnp.einsum("td,edh->eth", t, w1)
        if b1 is not None:
            h = h + b1[:, None, :]
        # swiglu if ffn1 packs 2x hidden, else gelu
        if w1.shape[-1] == 2 * w2.shape[1]:
            a, b = jnp.split(h, 2, axis=-1)
            h = jax.nn.silu(a) * b
        else:
            h = jax.nn.gelu(h)
        y = jnp.einsum("eth,ehd->etd", h, w2)
        if b2 is not None:
            y = y + b2[:, None, :]
        out = jnp.einsum("etd,te->td", y, full.astype(y.dtype))
        return out.reshape(orig)

    inputs = [x, gate_weight, ffn1_weight, ffn2_weight]
    for extra in (ffn1_bias, ffn2_bias, gate_bias):
        if extra is not None:
            inputs.append(extra)
    return apply_op("fused_moe", fn, inputs)


def masked_multihead_attention(x, cache_kv, seq_lens, scale=None, **kw):
    """Single-token decode attention over a dense KV cache (reference:
    python/paddle/incubate/nn/functional/masked_multihead_attention.py, CUDA
    kernel phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu).

    x: [b, 3, nh, hd] packed qkv for the new token; cache_kv: [2, b, nh, S, hd]
    (paddle's cache layout).  Returns (out [b, nh, hd], new cache_kv, new
    seq_lens) — functional instead of the reference's in-place `_` op."""
    from ....ops import decode_attention as _da

    def fn(xv, cache, lens):
        out, ck, cv, nl = _da.masked_multihead_attention(
            xv, cache[0], cache[1], lens, scale=scale)
        return out, jnp.stack([ck, cv]), nl

    return apply_op("masked_multihead_attention", fn, [x, cache_kv, seq_lens])


def block_multihead_attention(q, key_cache, value_cache, block_tables,
                              seq_lens, scale=None, kv_quant=None,
                              k_scale=None, v_scale=None, **kw):
    """Paged (block) KV-cache decode attention (reference:
    python/paddle/incubate/nn/functional/block_multihead_attention.py,
    fused_ops.yaml:45).  See ops/decode_attention.py for layout.

    Routed through :func:`ops.decode_attention.paged_decode_attention`, so
    GQA head groups, int8/int4 quantized KV pages (``kv_quant`` +
    ``k_scale``/``v_scale``), and the ragged Pallas kernel dispatch all
    apply here too (disable with PADDLE_TPU_DISABLE_PALLAS=paged_attention)."""
    from ....ops import decode_attention as _da

    def fn(qv, kc, vc, bt, lens, *scales):
        ks, vs = scales if scales else (None, None)
        return _da.paged_decode_attention(qv, kc, vc, bt, lens, scale=scale,
                                          kv_quant=kv_quant, k_scale=ks,
                                          v_scale=vs)

    inputs = [q, key_cache, value_cache, block_tables, seq_lens]
    if kv_quant:
        inputs += [k_scale, v_scale]
    return apply_op("block_multihead_attention", fn, inputs)


def fused_multi_transformer(
    x, ln_scales, ln_biases, qkv_weights, qkv_biases,
    linear_weights, linear_biases, ffn_ln_scales, ffn_ln_biases,
    ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases,
    pre_layer_norm=True, epsilon=1e-5, cache_kvs=None, pre_caches=None,
    rotary_embs=None, time_step=None, attn_mask=None, dropout_rate=0.0,
    rotary_emb_dims=0, activation="gelu", training=False,
    mode="upscale_in_train", use_neox_rotary_style=False, gqa_group_size=-1,
    norm_type="layernorm", trans_qkvw=True, name=None,
):
    """The reference's whole-decoder fused op (fused_ops.yaml:394,
    python/paddle/incubate/nn/functional/fused_transformer.py
    fused_multi_transformer): L pre/post-LN transformer layers with one call,
    threading a dense KV cache for generation.

    TPU mapping: one jnp composition that XLA fuses per layer — the CUDA
    kernel's fusion work is the compiler's job here; the op's value on TPU is
    the *cache-threading decode semantics*: prefill writes cache positions
    [pre_len, pre_len + s) (pre_len = 0 without ``pre_caches``), and decode
    with ``time_step=t`` appends the single new token at cache position
    pre_len + t and attends over the first pre_len + t + 1 slots.
    ``time_step`` is PROMPT-RELATIVE — it counts tokens after the prefix,
    which the op offsets internally (rotary positions included).
    ``pre_caches`` ([2, b, nh_or_kvh, pre_len, hd] per layer) is a
    read-only prefix KV (prefix tuning / shared system prompt) committed
    into the main cache at prefill; it requires ``cache_kvs``.
    ``norm_type`` selects layernorm | rmsnorm; ``trans_qkvw=False`` accepts
    the dim_embed-first qkv weight layout.

    Shapes (reference layout): x [b, s, e]; qkv_weights[i] [3, nh, hd, e]
    (MHA) or, with ``gqa_group_size=kvh`` kv heads, [nh + 2*kvh, hd, e]
    (the reference's GQA packing, infermeta/fusion.cc:195);
    linear_weights[i] [nh*hd, e]; ffn1 [e, di]; ffn2 [di, e];
    cache_kvs[i] [2, b, nh_or_kvh, S, hd].  ``rotary_embs`` [2, b, 1, S, hd]
    holds (cos, sin) per position; ``use_neox_rotary_style`` selects
    half-rotation (NeoX) vs interleaved-pair (GPT-J) application.  Returns
    (out, cache_kvs) when caches are given, else out — functional in place
    of the reference's in-place ``_``.
    """
    import jax
    import numpy as np

    if dropout_rate and training:
        raise NotImplementedError(
            "fused_multi_transformer: dropout in training mode is not "
            "implemented (inference/serving op here); use the nn.Layer stack "
            "for dropout training")
    if rotary_emb_dims not in (0, 1):
        raise NotImplementedError(
            "fused_multi_transformer: rotary_emb_dims=2 (2D/GLM rotary with "
            "pos_extra_ids) is not supported")
    if rotary_emb_dims == 1 and rotary_embs is None:
        raise ValueError("rotary_emb_dims=1 requires rotary_embs")
    if rotary_embs is not None and rotary_emb_dims == 0:
        # the reference kernel's rotary loop runs rotary_emb_dims times, so
        # dims=0 would silently IGNORE the supplied table — reject instead
        raise ValueError(
            "rotary_embs given but rotary_emb_dims=0 (the reference ignores "
            "the table in this case); pass rotary_emb_dims=1 to apply rotary")
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[activation]
    L = len(qkv_weights)
    use_cache = cache_kvs is not None
    decode = time_step is not None
    use_rotary = rotary_embs is not None and rotary_emb_dims > 0
    gqa = gqa_group_size > 0
    use_pre = pre_caches is not None
    if use_pre and not use_cache:
        raise ValueError(
            "fused_multi_transformer: pre_caches requires cache_kvs (the "
            "prefix is committed into the main cache at prefill)")
    pre_len = int(pre_caches[0].shape[3]) if use_pre else 0

    def apply_rotary(u, cos, sin):
        # u [b, s, n, hd]; cos/sin [b, s, hd] (broadcast over heads)
        cos = cos[:, :, None]
        sin = sin[:, :, None]
        if use_neox_rotary_style:
            u1, u2 = jnp.split(u, 2, axis=-1)
            rot = jnp.concatenate([-u2, u1], axis=-1)
        else:
            # GPT-J interleaved pairs: (x0, x1) -> (-x1, x0)
            rot = jnp.stack([-u[..., 1::2], u[..., 0::2]],
                            axis=-1).reshape(u.shape)
        return u * cos + rot * sin

    if norm_type not in ("layernorm", "rmsnorm"):
        raise NotImplementedError(f"norm_type {norm_type!r} not supported "
                                  "(layernorm | rmsnorm)")

    def ln(v, scale_, bias_, eps):
        if norm_type == "rmsnorm":
            # llama-family serving (reference fused_transformer.py:1302):
            # the shared Pallas rms_norm kernel (f32-internal custom VJP)
            out = _rms.rms_norm(v, scale_, eps)
            return out + bias_ if bias_ is not None else out
        mu = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        out = (v - mu) / jnp.sqrt(var + eps)
        return out * scale_ + (bias_ if bias_ is not None else 0.0)

    def one_layer(xv, lns, lnb, qkvw, qkvb, lw, lb, flns, flnb, f1w, f1b,
                  f2w, f2b, cache, t, rot, pre=None):
        b, s, e = xv.shape
        if not trans_qkvw:
            # reference's untransposed layout puts dim_embed FIRST
            # ([e, 3, nh, hd] / [e, nh+2kvh, hd], fused_ops.yaml:190 attr)
            qkvw = jnp.moveaxis(qkvw, 0, -1)
        h = ln(xv, lns, lnb, epsilon) if pre_layer_norm else xv
        if gqa:
            # GQA packing [nh + 2*kvh, hd, e] (infermeta/fusion.cc:195)
            total, hd, _ = qkvw.shape
            kvh = gqa_group_size
            nh = total - 2 * kvh
            qkv = jnp.einsum("bse,nde->bsnd", h, qkvw)  # [b, s, nh+2kvh, hd]
            if qkvb is not None:
                qkv = qkv + qkvb[None, None]
            q = qkv[:, :, :nh]
            k = qkv[:, :, nh:nh + kvh]
            v = qkv[:, :, nh + kvh:]
        else:
            _, nh, hd, _ = qkvw.shape
            qkv = jnp.einsum("bse,cnde->bscnd", h, qkvw)  # [b, s, 3, nh, hd]
            if qkvb is not None:
                qkv = qkv + qkvb[None, None]
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b, s, nh, hd]
        if rot is not None:
            # rot [2, b, 1, S, hd]: slice this call's ABSOLUTE positions —
            # [pre_len, pre_len + s) for prefill, pre_len + t for the single
            # decode token (a pre_caches prefix occupies positions
            # [0, pre_len), so new tokens continue after it; without a
            # prefix these reduce to [0, s) and t)
            if decode:
                cs = jax.lax.dynamic_slice_in_dim(rot[:, :, 0], t + pre_len,
                                                  1, axis=2)
            else:
                cs = rot[:, :, 0, pre_len:pre_len + s]
            cos_p, sin_p = cs[0], cs[1]                # [b, s, hd]
            q = apply_rotary(q, cos_p, sin_p)
            k = apply_rotary(k, cos_p, sin_p)
        # causal is the DEFAULT only when no attn_mask is given (the
        # reference op applies solely the caller's mask — an encoder-style
        # bidirectional mask must be expressible); cache-validity bounds are
        # structural and always apply
        causal_default = attn_mask is None
        if use_cache:
            S = cache.shape[3]
            if decode:
                # append the single new token at position pre_len + t;
                # slots past it are unwritten garbage and always masked
                cache = jax.lax.dynamic_update_slice(
                    cache, jnp.stack([k, v]).transpose(0, 1, 3, 2, 4),
                    (0, 0, 0, t + pre_len, 0))
                kk = cache[0]
                vv = cache[1]
                kv_mask = jnp.arange(S)[None, None, None, :] <= t + pre_len
            else:
                if pre is not None:
                    # commit the read-only prefix KV (prefix tuning /
                    # system prompt — reference pre_caches) into slots
                    # [0, pre_len) so decode attends over it for free
                    cache = jax.lax.dynamic_update_slice(
                        cache, jnp.asarray(pre, cache.dtype), (0, 0, 0, 0, 0))
                cache = jax.lax.dynamic_update_slice(
                    cache, jnp.stack([k, v]).transpose(0, 1, 3, 2, 4),
                    (0, 0, 0, pre_len, 0))
                kk = cache[0]
                vv = cache[1]
                q_pos = jnp.arange(s)[None, None, :, None]
                idx = jnp.arange(S)[None, None, None, :]
                valid = idx < pre_len + s
                # prefix slots (idx < pre_len) are visible to every query;
                # the written region stays causal in prompt-relative terms
                kv_mask = (valid & (idx - pre_len <= q_pos)
                           if causal_default else valid)
        else:
            kk = k.transpose(0, 2, 1, 3)
            vv = v.transpose(0, 2, 1, 3)
            if causal_default:
                q_pos = jnp.arange(s)[None, None, :, None]
                kv_mask = jnp.arange(s)[None, None, None, :] <= q_pos
            else:
                kv_mask = jnp.ones((1, 1, 1, s), bool)
        if gqa:
            # grouped heads contract against the UN-replicated kv cache
            # (query head h uses kv head h // grp — jnp.repeat semantics
            # without materializing an nh-wide K/V)
            grp = nh // gqa_group_size
            qg = q.reshape(b, s, gqa_group_size, grp, hd)
            logits = jnp.einsum("bsngd,bnSd->bngsS", qg.astype(jnp.float32),
                                kk.astype(jnp.float32)) / np.sqrt(hd)
            logits = logits.reshape(b, nh, s, logits.shape[-1])
        else:
            logits = jnp.einsum("bsnd,bnSd->bnsS", q.astype(jnp.float32),
                                kk.astype(jnp.float32)) / np.sqrt(hd)
        logits = jnp.where(kv_mask, logits, -1e30)
        if attn_mask is not None:
            logits = logits + jnp.asarray(_unwrap(attn_mask), logits.dtype)
        p = jax.nn.softmax(logits, axis=-1)
        if gqa:
            p5 = p.reshape(b, gqa_group_size, grp, s, p.shape[-1])
            attn = jnp.einsum("bngsS,bnSd->bsngd", p5.astype(vv.dtype),
                              vv).reshape(b, s, nh, hd)
        else:
            attn = jnp.einsum("bnsS,bnSd->bsnd", p.astype(vv.dtype), vv)
        attn = attn.reshape(b, s, nh * hd) @ lw
        if lb is not None:
            attn = attn + lb
        xv = xv + attn
        if not pre_layer_norm:
            xv = ln(xv, lns, lnb, epsilon)
        h = ln(xv, flns, flnb, epsilon) if pre_layer_norm else xv
        ff = act(h @ f1w + (f1b if f1b is not None else 0.0)) @ f2w
        if f2b is not None:
            ff = ff + f2b
        xv = xv + ff
        if not pre_layer_norm:
            xv = ln(xv, flns, flnb, epsilon)
        return xv, cache

    def fn(xv, *flat):
        t = None
        if decode:
            t = jnp.asarray(_unwrap(time_step), jnp.int32).reshape(())
        per = 12  # tensors per layer in `flat` (before caches/pre/rotary)
        rot = flat[-1] if use_rotary else None
        if use_rotary:
            flat = flat[:-1]
        pres = list(flat[-L:]) if use_pre else [None] * L
        if use_pre:
            flat = flat[:-L]
        caches = list(flat[per * L:]) if use_cache else [None] * L
        new_caches = []
        out = xv
        for i in range(L):
            lns, lnb, qkvw, qkvb, lw, lb, flns, flnb, f1w, f1b, f2w, f2b = (
                flat[per * i: per * (i + 1)])
            out, c = one_layer(out, lns, lnb, qkvw, qkvb, lw, lb, flns, flnb,
                               f1w, f1b, f2w, f2b, caches[i], t, rot, pres[i])
            new_caches.append(c)
        if use_cache:
            return tuple([out] + new_caches)
        return out

    def opt(seq, i):
        return seq[i] if seq is not None else None

    flat = []
    for i in range(L):
        flat.extend([
            ln_scales[i], opt(ln_biases, i), qkv_weights[i], opt(qkv_biases, i),
            linear_weights[i], opt(linear_biases, i),
            ffn_ln_scales[i], opt(ffn_ln_biases, i),
            ffn1_weights[i], opt(ffn1_biases, i),
            ffn2_weights[i], opt(ffn2_biases, i),
        ])
    # None biases become inline 0-d zeros in x's dtype (a float32 zero would
    # silently promote a bf16 residual stream through every bias add)
    xdt = _unwrap(x).dtype
    flat = [f if f is not None else jnp.zeros((), xdt) for f in flat]
    inputs = ([x] + flat + (list(cache_kvs) if use_cache else [])
              + (list(pre_caches) if use_pre else [])
              + ([rotary_embs] if use_rotary else []))
    res = apply_op("fused_multi_transformer", fn, inputs)
    if use_cache:
        return res[0], list(res[1:])
    return res


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """fused_matmul_bias.py: matmul + bias add in one op (cublasLt epilogue
    on the reference; one fused XLA dot here)."""
    def fn(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out

    ins = [x, y] + ([bias] if bias is not None else [])
    return apply_op("fused_matmul_bias", fn, ins)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """fused_dropout_add.py: dropout(x) + y without materializing the
    intermediate (XLA fuses the mask/scale/add)."""
    from ....core import rng as _rng

    def fn(a, b):
        if not training or p == 0.0:
            out = a if mode == "upscale_in_train" else a * (1.0 - p)
            return out + b
        keep = jax.random.bernoulli(_rng.next_key(), 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            a = jnp.where(keep, a / (1.0 - p), 0.0)
        else:
            a = jnp.where(keep, a, 0.0)
        return a + b

    return apply_op("fused_dropout_add", fn, [x, y])


def fused_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                is_causal=False, training=True,
                                scaling_factor=None, name=None):
    """fused_dot_product_attention.py (cuDNN fused attention on the
    reference): BSHD q/k/v -> BSHD out, optional additive mask / causal."""
    import math as _math

    def fn(qv, kv, vv, *rest):
        b, s, h, d = qv.shape
        scale = scaling_factor if scaling_factor is not None else 1.0 / _math.sqrt(d)
        logits = jnp.einsum("bshd,bShd->bhsS", qv.astype(jnp.float32),
                            kv.astype(jnp.float32)) * scale
        if rest:
            m = rest[0]
            logits = (jnp.where(m, logits, -1e30) if m.dtype == jnp.bool_
                      else logits + m.astype(logits.dtype))
        if is_causal:
            S = kv.shape[1]
            cm = jnp.arange(S)[None, :] <= jnp.arange(s)[:, None]
            logits = jnp.where(cm[None, None], logits, -1e30)
        w = jax.nn.softmax(logits, axis=-1)
        if dropout_p and training:
            from ....core import rng as _rng
            keep = jax.random.bernoulli(_rng.next_key(), 1.0 - dropout_p,
                                        w.shape)
            w = jnp.where(keep, w / (1.0 - dropout_p), 0.0)
        return jnp.einsum("bhsS,bShd->bshd", w.astype(vv.dtype), vv)

    ins = [q, k, v] + ([attn_mask] if attn_mask is not None else [])
    return apply_op("fused_dot_product_attention", fn, ins)


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False, name=None):
    """fused_gate_attention.py:26 (AlphaFold gated MSA self-attention; the
    docstring's einsum program executed verbatim): query [n, b, q, a],
    per-head projections, optional nonbatched bias, sigmoid gating on the
    weighted average, and the output projection."""
    ins = [query]
    names = []
    for nm, t in (("key", key), ("qw", query_weight), ("kw", key_weight),
                  ("vw", value_weight), ("qkvw", qkv_weight),
                  ("gw", gate_linear_weight), ("gb", gate_linear_bias),
                  ("ow", out_linear_weight), ("ob", out_linear_bias),
                  ("nbias", nonbatched_bias), ("mask", attn_mask)):
        if t is not None:
            ins.append(t)
            names.append(nm)

    def fn(qd, *rest):
        g = dict(zip(names, rest))
        m_data = g.get("key", qd)
        if merge_qkv:
            # qkv_weight [3, heads, head_dim, a]
            qw = jnp.moveaxis(g["qkvw"][0], -1, 0)   # [a, h, c]
            kw = jnp.moveaxis(g["qkvw"][1], -1, 0)
            vw = jnp.moveaxis(g["qkvw"][2], -1, 0)
        else:
            qw, kw, vw = g["qw"], g["kw"], g["vw"]
        c = qw.shape[-1] ** -0.5
        q = jnp.einsum("nbqa,ahc->nbqhc", qd, qw) * c
        k = jnp.einsum("nbka,ahc->nbkhc", m_data, kw)
        v = jnp.einsum("nbka,ahc->nbkhc", m_data, vw)
        logits = jnp.einsum("nbqhc,nbkhc->nbhqk",
                            q.astype(jnp.float32), k.astype(jnp.float32))
        if "mask" in g:
            # [n, b, 1, 1, k] additive mask
            logits = logits + g["mask"].astype(logits.dtype)
        if "nbias" in g:
            logits = logits + jnp.expand_dims(g["nbias"], 1).astype(logits.dtype)
        w = jax.nn.softmax(logits, axis=-1)
        avg = jnp.einsum("nbhqk,nbkhc->nbqhc", w.astype(v.dtype), v)
        if has_gating:
            gate = jnp.einsum("nbqc,chv->nbqhv", qd, g["gw"]) + g["gb"]
            avg = avg * jax.nn.sigmoid(gate)
        out = jnp.einsum("nbqhc,hco->nbqo", avg, g["ow"])
        if "ob" in g:
            out = out + g["ob"]
        return out

    return apply_op("fused_gate_attention", fn, ins)


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size,
                     name=None):
    """blha_get_max_len.py: max encoder/decoder lengths for the block
    attention launch config (two scalar maxes)."""
    def fn(e, d):
        return jnp.max(e).reshape(1), jnp.max(d).reshape(1)

    return apply_op("blha_get_max_len", fn,
                    [seq_lens_encoder, seq_lens_decoder], n_outputs=2)
