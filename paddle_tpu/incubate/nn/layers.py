"""Fused Layer classes (reference: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention :213, FusedFeedForward :534,
FusedTransformerEncoderLayer :750, FusedMultiTransformer :1071;
fused_linear.py FusedLinear :26; fused_dropout_add.py FusedDropoutAdd :26).

Each Layer owns the parameters and forwards through the functional fused op
in ``incubate.nn.functional`` — same split as the reference (Layer = param
container, functional = the fused kernel call).
"""

from __future__ import annotations

from ... import nn
from . import functional as F

__all__ = [
    "FusedLinear",
    "FusedDropoutAdd",
    "FusedBiasDropoutResidualLayerNorm",
    "FusedMultiHeadAttention",
    "FusedFeedForward",
    "FusedTransformerEncoderLayer",
    "FusedMultiTransformer",
]


class FusedLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter((out_features,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              transpose_weight=self.transpose_weight)


class FusedDropoutAdd(nn.Layer):
    """out = dropout(x) + y (reference fused_dropout_add.py:26)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return nn.functional.dropout(x, p=self.p, training=self.training,
                                     mode=self.mode) + y


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """out = layer_norm(residual + dropout(x + bias)) (reference :94)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn import initializer as I

        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter((embed_dim,), is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr, default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), attr=bias_attr,
                                             is_bias=True)

    def forward(self, x, residual):
        h = nn.functional.dropout(x + self.linear_bias, p=self.dropout_rate,
                                  training=self.training)
        return nn.functional.layer_norm(
            residual + h, x.shape[-1:], self.ln_scale, self.ln_bias,
            self.epsilon)


class FusedMultiHeadAttention(nn.Layer):
    """Param container over functional.fused_multi_head_attention
    (reference :213)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        from ...nn import initializer as I

        assert embed_dim > 0 and num_heads > 0
        assert embed_dim % num_heads == 0
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        self.transpose_qkv_wb = transpose_qkv_wb
        if transpose_qkv_wb:
            qkv_shape = (embed_dim, 3 * embed_dim)
        else:
            qkv_shape = (3, num_heads, self.head_dim, embed_dim)
        self.qkv_weight = self.create_parameter(qkv_shape, attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            (3 * embed_dim,) if transpose_qkv_wb else (3, num_heads, self.head_dim),
            attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter((embed_dim, embed_dim),
                                                   attr=linear_weight_attr)
        self.linear_bias = self.create_parameter((embed_dim,),
                                                 attr=linear_bias_attr, is_bias=True)
        one = I.Constant(1.0)
        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), attr=pre_ln_scale_attr, default_initializer=one)
        self.pre_ln_bias = self.create_parameter((embed_dim,),
                                                 attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=ln_scale_attr, default_initializer=one)
        self.ln_bias = self.create_parameter((embed_dim,), attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        # the fused op is self-attention (same contract as the reference's
        # fused kernel, which asserts key is query); fail loudly rather than
        # silently ignoring a distinct key/value
        if (key is not None and key is not query) or \
                (value is not None and value is not query):
            raise NotImplementedError(
                "FusedMultiHeadAttention is self-attention only "
                "(key/value must be None or the query tensor)")
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self.epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate, ln_epsilon=self.epsilon,
            training=self.training, transpose_qkv_wb=self.transpose_qkv_wb,
            num_heads=self.num_heads)


class FusedFeedForward(nn.Layer):
    """[pre/post LN] linear -> act -> dropout -> linear -> dropout + residual
    (reference :534)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, name=None):
        super().__init__()
        from ...nn import initializer as I

        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            (dim_feedforward,), attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            (d_model,), attr=linear2_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            (d_model,), attr=ln1_scale_attr, default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((d_model,), attr=ln1_bias_attr,
                                             is_bias=True)

    def forward(self, src):
        residual = src
        h = src
        if self.normalize_before:
            h = nn.functional.layer_norm(h, h.shape[-1:], self.ln_scale,
                                         self.ln_bias, self.epsilon)
        h = F.fused_linear(h, self.linear1_weight, self.linear1_bias)
        h = getattr(nn.functional, self.activation)(h)
        h = nn.functional.dropout(h, p=self.act_dropout_rate,
                                  training=self.training)
        h = F.fused_linear(h, self.linear2_weight, self.linear2_bias)
        h = nn.functional.dropout(h, p=self.dropout_rate,
                                  training=self.training)
        out = residual + h
        if not self.normalize_before:
            out = nn.functional.layer_norm(out, out.shape[-1:], self.ln_scale,
                                           self.ln_bias, self.epsilon)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    """FusedMultiHeadAttention + FusedFeedForward (reference :750)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        if isinstance(out, tuple):  # decode path returns (out, new_cache)
            attn_out, new_cache = out
            return self.ffn(attn_out), new_cache
        return self.ffn(out)


class FusedMultiTransformer(nn.Layer):
    """num_layers decoder layers over functional.fused_multi_transformer
    (reference :1071, the serving stack's Layer)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None, epsilon=1e-5,
                 residual_alpha=1.0, num_layers=-1, nranks=1, trans_qkvw=True,
                 ring_id=-1, norm_type="layernorm",
                 use_neox_rotary_style=False, gqa_group_size=-1, name=None):
        super().__init__()
        from ...nn import initializer as I

        # unsupported reference variants fail loudly instead of silently
        # building the wrong computation
        self.trans_qkvw = bool(trans_qkvw)
        if norm_type not in ("layernorm", "rmsnorm"):
            raise NotImplementedError(
                f"norm_type {norm_type!r} not supported (layernorm | rmsnorm)")
        self.norm_type = norm_type
        if residual_alpha != 1.0:
            raise NotImplementedError("residual_alpha != 1.0 not supported")
        assert embed_dim > 0 and num_heads > 0
        if gqa_group_size > 0 and num_heads % gqa_group_size:
            raise ValueError(
                f"num_heads={num_heads} must divide by "
                f"gqa_group_size={gqa_group_size} (kv heads)")
        self.use_neox_rotary_style = use_neox_rotary_style
        self.gqa_group_size = gqa_group_size
        if num_layers < 0:
            num_layers = (len(qkv_weight_attrs)
                          if isinstance(qkv_weight_attrs, (list, tuple)) else 1)
        self.num_layers = num_layers
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        nh, hd = num_heads, embed_dim // num_heads
        one = I.Constant(1.0)

        def attr_i(attrs, i):
            return attrs[i] if isinstance(attrs, (list, tuple)) else attrs

        def plist(name, shape, attrs, bias=False, init=None):
            ps = []
            for i in range(num_layers):
                p = self.create_parameter(shape, attr=attr_i(attrs, i),
                                          is_bias=bias,
                                          default_initializer=init)
                self.add_parameter(f"{name}_{i}", p)
                ps.append(p)
            return ps

        self.ln_scales = plist("ln_scale", (embed_dim,), ln_scale_attrs, init=one)
        self.ln_biases = plist("ln_bias", (embed_dim,), ln_bias_attrs, bias=True)
        if gqa_group_size > 0:
            # GQA packing: q heads then kv heads (infermeta/fusion.cc:195)
            qkv_shape = (nh + 2 * gqa_group_size, hd, embed_dim)
            qkv_b_shape = (nh + 2 * gqa_group_size, hd)
        else:
            qkv_shape = (3, nh, hd, embed_dim)
            qkv_b_shape = (3, nh, hd)
        if not trans_qkvw:
            # untransposed layout: dim_embed leads (fused_ops.yaml:190)
            qkv_shape = (embed_dim,) + qkv_shape[:-1]
        self.qkv_weights = plist("qkv_weight", qkv_shape, qkv_weight_attrs)
        self.qkv_biases = plist("qkv_bias", qkv_b_shape, qkv_bias_attrs, bias=True)
        self.linear_weights = plist("linear_weight", (nh * hd, embed_dim),
                                    linear_weight_attrs)
        self.linear_biases = plist("linear_bias", (embed_dim,),
                                   linear_bias_attrs, bias=True)
        self.ffn_ln_scales = plist("ffn_ln_scale", (embed_dim,),
                                   ffn_ln_scale_attrs, init=one)
        self.ffn_ln_biases = plist("ffn_ln_bias", (embed_dim,),
                                   ffn_ln_bias_attrs, bias=True)
        self.ffn1_weights = plist("ffn1_weight", (embed_dim, dim_feedforward),
                                  ffn1_weight_attrs)
        self.ffn1_biases = plist("ffn1_bias", (dim_feedforward,),
                                 ffn1_bias_attrs, bias=True)
        self.ffn2_weights = plist("ffn2_weight", (dim_feedforward, embed_dim),
                                  ffn2_weight_attrs)
        self.ffn2_biases = plist("ffn2_bias", (embed_dim,), ffn2_bias_attrs,
                                 bias=True)

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, time_step=None):
        return F.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self.normalize_before, epsilon=self.epsilon,
            cache_kvs=caches, pre_caches=pre_caches, rotary_embs=rotary_embs,
            time_step=time_step, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate,
            rotary_emb_dims=rotary_emb_dims, activation=self.activation,
            training=self.training,
            use_neox_rotary_style=self.use_neox_rotary_style,
            gqa_group_size=self.gqa_group_size, norm_type=self.norm_type,
            trans_qkvw=self.trans_qkvw)
