from . import functional  # noqa: F401
from .layers import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedDropoutAdd,
    FusedFeedForward,
    FusedLinear,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformerEncoderLayer,
)
