"""Incubate optimizers (reference: python/paddle/incubate/optimizer/
lookahead.py:36, modelaverage.py:42)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import _unwrap
from ..optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k steps forward, 1 step back (lookahead.py:36): every ``k`` inner
    steps the slow weights move α of the way toward the fast weights, and
    the fast weights reset to the slow ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        params = inner_optimizer._parameter_list or []
        super().__init__(learning_rate=inner_optimizer._lr, parameters=params)
        # slow weights snapshot LAZILY at the first step (the reference's
        # accumulator init): weights loaded after construction
        # (set_state_dict) must seed the slow copy, not the init-time values
        self._slow: dict[int, jnp.ndarray] = {}
        self._k_count = 0

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def step(self):
        if not self._slow:
            self._slow = {id(p): _unwrap(p) for p in self._parameter_list}
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k == 0:
            for p in self._parameter_list:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (_unwrap(p) - slow)
                self._slow[id(p)] = slow
                p._value = slow.astype(p.dtype)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage(Optimizer):
    """Running average of parameters over a sliding window
    (modelaverage.py:42): accumulates sums, apply() swaps the averaged
    weights in (optionally restorable)."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=list(parameters or []))
        self.avg_rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._sum: dict[int, jnp.ndarray] = {}
        self._count = 0
        self._backup: dict[int, jnp.ndarray] = {}

    def step(self):
        """Fold the CURRENT parameter values into the running sums (called
        after the training optimizer's step)."""
        self._count += 1
        for p in self._parameter_list:
            v = _unwrap(p).astype(jnp.float32)
            acc = self._sum.get(id(p))
            self._sum[id(p)] = v if acc is None else acc + v
        # restart the window like the reference when it overruns
        window = max(self.min_window,
                     min(self.max_window, int(self._count * self.avg_rate)))
        if self._count > window + self.max_window:
            self._sum = {id(p): _unwrap(p).astype(jnp.float32)
                         for p in self._parameter_list}
            self._count = 1

    def apply(self, executor=None, need_restore=True):
        """Context manager swapping in the averaged weights."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            if self._count == 0:
                raise RuntimeError("ModelAverage.apply before any step()")
            for p in self._parameter_list:
                self._backup[id(p)] = _unwrap(p)
                p._value = (self._sum[id(p)] / self._count).astype(p.dtype)
            try:
                yield self
            finally:
                if need_restore:
                    self.restore()

        return cm()

    def restore(self, executor=None):
        for p in self._parameter_list:
            if id(p) in self._backup:
                p._value = self._backup.pop(id(p))

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
