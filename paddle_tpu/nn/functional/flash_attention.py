"""Flash-attention functional family (reference:
python/paddle/nn/functional/flash_attention.py).

The reference routes these through CUDA flash-attn kernels; on TPU the same
contract is met by the Pallas flash kernel (ops/pallas/flash_attention.py)
when it applies, falling back to an XLA-composed masked attention that the
compiler fuses and tiles onto the MXU.  All entry points run through
``apply_op`` so eager autograd records them on the tape.
"""

from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp

from ...core import rng
from ...core.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "flash_attention",
    "flash_attn_qkvpacked",
    "flash_attn_unpadded",
    "flash_attn_varlen_qkvpacked",
    "flashmask_attention",
    "calc_reduced_attention_scores",
    "sdp_kernel",
]


def sdp_kernel(enable_math=False, enable_flash=True, enable_mem_efficient=True):
    """No-op context manager kept for parity (flash_attention.py:144): TPU
    dispatch is decided by FLAGS_use_pallas_kernels, not a CUDA-arch probe."""
    import contextlib

    return contextlib.nullcontext()


def _dropout_probs(probs, dropout, training):
    if dropout and training:
        keep = jax.random.bernoulli(rng.next_key(), 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    return probs


def _dense_attention(q, k, v, mask, causal, scale, dropout, training,
                     return_softmax, causal_align="br"):
    """Masked attention core on [B, S, H, D] (paddle layout) — the single
    implementation behind the flash family, scaled_dot_product_attention's
    XLA path, and sparse_attention.  ``mask`` is broadcastable
    [B|1, H|1, Sq, Sk]: boolean (True = attend) or additive float bias.
    ``causal_align``: "br" = bottom-right (flash-attn convention for
    sq != sk), "tl" = top-left (torch/paddle sdpa convention)."""
    qh = jnp.swapaxes(q, 1, 2)  # [b, h, s, d]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    nq, nk = qh.shape[1], kh.shape[1]
    if nq != nk:  # GQA: repeat kv heads onto the query-head axis
        kh = jnp.repeat(kh, nq // nk, axis=1)
        vh = jnp.repeat(vh, nq // nk, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        off = (sk - sq) if causal_align == "br" else 0
        tri = jnp.tril(jnp.ones((sq, sk), bool), k=off)
        logits = jnp.where(tri, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    # fully-masked rows produce NaN from softmax(-inf row); zero them like
    # the reference kernel does for padding queries
    probs = jnp.nan_to_num(probs, nan=0.0)
    probs = _dropout_probs(probs, dropout, training).astype(q.dtype)
    out = jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", probs, vh), 1, 2)
    return (out, probs) if return_softmax else (out,)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, *, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """flash_attention.py:358 — [B, S, H, D] in, (out, softmax|None) out."""
    from . import scaled_dot_product_attention

    # sdpa's causal mask is top-left aligned (the torch/paddle sdpa
    # convention); flash_attention follows the flash-attn kernel convention
    # of BOTTOM-RIGHT alignment when sq != sk, so only delegate on equal
    # lengths where the two agree
    if not return_softmax and not dropout and \
            int(query.shape[1]) == int(key.shape[1]):
        out = scaled_dot_product_attention(query, key, value,
                                           is_causal=causal, training=training)
        return out, None
    scale = 1.0 / _math.sqrt(int(query.shape[-1]))

    def fn(q, k, v):
        return _dense_attention(q, k, v, None, causal, scale, dropout,
                                training, return_softmax)

    res = apply_op("flash_attention", fn, [query, key, value])
    if return_softmax:
        return res[0], res[1]
    return res[0], None


def _split_qkvpacked(qkv):
    """[..., G+2, NKV, D] → q [..., G*NKV, D], k/v [..., NKV, D] (packed
    layout documented at flash_attention.py:632)."""
    g = int(qkv.shape[-3]) - 2
    q = qkv[..., :g, :, :].reshape(*qkv.shape[:-3], g * int(qkv.shape[-2]),
                                   int(qkv.shape[-1]))
    return q, qkv[..., g, :, :], qkv[..., g + 1, :, :]


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         *, fixed_seed_offset=None, rng_name="",
                         training=True, name=None):
    """flash_attention.py:590 — qkv [B, S, G+2, NKV, D]."""
    scale = 1.0 / _math.sqrt(int(qkv.shape[-1]))

    def fn(packed):
        q, k, v = _split_qkvpacked(packed)
        return _dense_attention(q, k, v, None, causal, scale, dropout,
                                training, return_softmax)

    res = apply_op("flash_attn_qkvpacked", fn, [qkv])
    if return_softmax:
        return res[0], res[1]
    return res[0], None


def _varlen_mask(cu_q, cu_k, sq, sk, causal):
    """Packed-layout segment mask: token i of the flat q buffer may attend
    token j of the flat k buffer iff they belong to the same sequence (and
    j's in-sequence position <= i's when causal)."""
    tq = jnp.arange(sq)
    tk = jnp.arange(sk)
    seg_q = jnp.searchsorted(cu_q[1:], tq, side="right")
    seg_k = jnp.searchsorted(cu_k[1:], tk, side="right")
    valid_q = tq < cu_q[-1]
    valid_k = tk < cu_k[-1]
    mask = (seg_q[:, None] == seg_k[None, :]) & valid_q[:, None] & valid_k[None, :]
    if causal:
        pos_q = tq - cu_q[seg_q]
        pos_k = tk - cu_k[seg_k]
        mask &= pos_k[None, :] <= pos_q[:, None]
    return mask[None, None]  # [1, 1, sq, sk]


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """flash_attention.py:756 — packed [total, H, D] with cu_seqlens
    boundaries; padding tokens (past cu_seqlens[-1]) produce zero output."""
    def fn(q, k, v, cu_q, cu_k):
        mask = _varlen_mask(cu_q, cu_k, q.shape[0], k.shape[0], causal)
        res = _dense_attention(q[None], k[None], v[None], mask, False, scale,
                               dropout, training, return_softmax)
        return tuple(r[0] for r in res)

    res = apply_op("flash_attn_unpadded", fn,
                   [query, key, value, cu_seqlens_q, cu_seqlens_k])
    if return_softmax:
        return res[0], res[1]
    return res[0], None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale, dropout=0.0, causal=False,
                                return_softmax=False, fixed_seed_offset=None,
                                rng_name="", varlen_padded=True, training=True,
                                name=None):
    """flash_attention.py:1011 — packed qkv [total, G+2, NKV, D].  With
    ``varlen_padded`` the buffer is batch-major padded to max_seqlen per
    sequence; either way attention is confined within each sequence."""
    def fn(packed, cu_q, cu_k):
        q, k, v = _split_qkvpacked(packed)
        if varlen_padded:
            b = q.shape[0] // int(max_seqlen_q)
            qb = q.reshape(b, int(max_seqlen_q), *q.shape[1:])
            kb = k.reshape(b, int(max_seqlen_k), *k.shape[1:])
            vb = v.reshape(b, int(max_seqlen_k), *v.shape[1:])
            len_q = (cu_q[1:] - cu_q[:-1])[:, None]
            len_k = (cu_k[1:] - cu_k[:-1])[:, None]
            ok_q = jnp.arange(int(max_seqlen_q))[None, :] < len_q
            ok_k = jnp.arange(int(max_seqlen_k))[None, :] < len_k
            mask = (ok_q[:, None, :, None] & ok_k[:, None, None, :])
            res = _dense_attention(qb, kb, vb, mask, causal, scale, dropout,
                                   training, return_softmax)
            out = res[0] * ok_q[..., None, None]  # zero padding rows
            out = out.reshape(q.shape)
            return (out,) + tuple(r.reshape(-1, *r.shape[2:]) for r in res[1:])
        mask = _varlen_mask(cu_q, cu_k, q.shape[0], k.shape[0], causal)
        res = _dense_attention(q[None], k[None], v[None], mask, False, scale,
                               dropout, training, return_softmax)
        return tuple(r[0] for r in res)

    res = apply_op("flash_attn_varlen_qkvpacked", fn,
                   [qkv, cu_seqlens_q, cu_seqlens_k])
    if return_softmax:
        return res[0], res[1]
    return res[0], None


def _flashmask_bands(idx, sq, sk, causal):
    """Column-band mask from startend_row_indices [B, KH, Sk, {1,2,4}]
    (flash_attention.py:1299): each column j carries row-bands that are
    DISALLOWED; returns True where attention is allowed."""
    rows = jnp.arange(sq)[None, None, :, None]  # broadcast [b, h, i, j]
    nb = int(idx.shape[-1])
    col = lambda n: idx[..., n][..., None, :]  # noqa: E731 — [B, KH, 1, Sk]

    if causal:
        if nb == 1:      # mask rows [LTS, inf)
            banned = rows >= col(0)
        elif nb == 2:    # mask rows [LTS, LTE)
            banned = (rows >= col(0)) & (rows < col(1))
        else:
            raise ValueError("causal flashmask expects last dim 1 or 2")
    else:
        if nb == 2:      # mask rows [LTS, inf) and [0, UTE)
            banned = (rows >= col(0)) | (rows < col(1))
        elif nb == 4:    # mask rows [LTS, LTE) and [UTS, UTE)
            banned = ((rows >= col(0)) & (rows < col(1))) | \
                     ((rows >= col(2)) & (rows < col(3)))
        else:
            raise ValueError("non-causal flashmask expects last dim 2 or 4")
    return ~banned


def flashmask_attention(query, key, value, startend_row_indices=None, *,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """flash_attention.py:1299 — column-wise sparse banded masking.  Bands
    are evaluated as a dense boolean mask; XLA folds it into the fused
    attention (the O(S^2) mask is bool, not a materialized score bias)."""
    if return_softmax_lse or return_seed_offset:
        raise NotImplementedError(
            "flashmask_attention: return_softmax_lse/return_seed_offset are "
            "CUDA-kernel introspection outputs not exposed by the TPU path")
    scale = 1.0 / _math.sqrt(int(query.shape[-1]))
    sq, sk = int(query.shape[1]), int(key.shape[1])
    if window_size is not None:
        window_size = ((window_size, window_size)
                       if isinstance(window_size, int) else tuple(window_size))

    inputs = [query, key, value]
    if startend_row_indices is not None:
        inputs.append(startend_row_indices)

    def fn(q, k, v, *rest):
        mask = None
        if rest:
            nkv = k.shape[2]
            idx = rest[0]
            if idx.shape[1] == 1 and nkv > 1:
                idx = jnp.broadcast_to(idx, (idx.shape[0], nkv) + idx.shape[2:])
            # repeat over the q-head grouping to match post-GQA head count
            idx = jnp.repeat(idx, q.shape[2] // idx.shape[1], axis=1)
            mask = _flashmask_bands(idx, sq, sk, causal)
        if window_size is not None:
            rows = jnp.arange(sq)[:, None]
            cols = jnp.arange(sk)[None, :]
            win = (rows - cols <= window_size[0]) & (cols - rows <= window_size[1])
            mask = win[None, None] if mask is None else mask & win[None, None]
        res = _dense_attention(q, k, v, mask, causal, scale, dropout,
                               training, False)
        return res[0]

    return apply_op("flashmask_attention", fn, inputs)


def calc_reduced_attention_scores(query, key, softmax_lse=None, name=None):
    """flash_attention.py:2033 — column-wise sum over queries of the softmax
    attention probabilities, reduced across q heads; [B, H, S, D] in (torch
    layout, matching the reference op), [B, 1, 1, Sk] out."""
    def fn(q, k, *rest):
        scale = 1.0 / _math.sqrt(q.shape[-1])
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        return jnp.sum(probs, axis=(1, 2), keepdims=True)

    inputs = [query, key] + ([softmax_lse] if softmax_lse is not None else [])
    return apply_op("calc_reduced_attention_scores", fn, inputs)
