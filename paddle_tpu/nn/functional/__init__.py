"""Functional neural-net ops (reference: python/paddle/nn/functional/ surface;
kernels: phi conv/pool/norm/softmax/activation families → XLA; fused LLM ops
live in paddle_tpu.incubate.nn.functional backed by Pallas)."""

from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dtype as dtypes
from ...core import rng
from ...core.tensor import Tensor, apply_op, _unwrap
from ...ops.manipulation import pad  # noqa: F401  (exported as F.pad)
from ...ops.manipulation import unfold_im2col as unfold  # noqa: F401  (F.unfold = im2col)
from ...ops.registry import register_op

__all__: list[str] = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# ============================ activations ============================

def _act(name, jfn):
    def op(x, name=None):
        return apply_op(name or op.__name__, jfn, [x])

    op.__name__ = name
    globals()[name] = op
    __all__.append(name)
    return op


_act("relu", jax.nn.relu)
_act("relu6", lambda v: jnp.clip(v, 0, 6))
_act("sigmoid", jax.nn.sigmoid)
_act("tanh", jnp.tanh)
_act("silu", jax.nn.silu)
_act("swish", jax.nn.silu)
_act("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)))
_act("softsign", jax.nn.soft_sign)
_act("tanhshrink", lambda v: v - jnp.tanh(v))
_act("log_sigmoid", jax.nn.log_sigmoid)
_act("hardswish", lambda v: v * jnp.clip(v + 3, 0, 6) / 6)


@_export
def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda v: jax.nn.gelu(v, approximate=approximate), [x])


@_export
def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu", lambda v: jax.nn.leaky_relu(v, negative_slope), [x])


@_export
def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda v: jax.nn.elu(v, alpha), [x])


@_export
def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda v: jax.nn.celu(v, alpha), [x])


@_export
def selu(
    x,
    scale=1.0507009873554804934193349852946,
    alpha=1.6732632423543772848170429916717,
    name=None,
):
    return apply_op(
        "selu", lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), [x]
    )


@_export
def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v > 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(v > 0, v, w.reshape(shape) * v)

    return apply_op("prelu", fn, [x, weight])


@_export
def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        "softplus",
        lambda v: jnp.where(v * beta > threshold, v, jax.nn.softplus(v * beta) / beta),
        [x],
    )


@_export
def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda v: jnp.where(v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, 0.0)),
        [x],
    )


@_export
def hardshrink(x, threshold=0.5, name=None):
    return apply_op(
        "hardshrink", lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), [x]
    )


@_export
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda v: jnp.clip(v, min, max), [x])


@_export
def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op(
        "thresholded_relu", lambda v: jnp.where(v > threshold, v, value), [x]
    )


@_export
def softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            v = v.astype(dtypes.convert_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)

    return apply_op("softmax", fn, [x])


@_export
def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            v = v.astype(dtypes.convert_dtype(dtype))
        return jax.nn.log_softmax(v, axis=axis)

    return apply_op("log_softmax", fn, [x])


@_export
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = rng.next_key()

    def fn(v):
        g = jax.random.gumbel(key, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            # straight-through: hard value forward, soft gradient backward
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return apply_op("gumbel_softmax", fn, [x])


@_export
def glu(x, axis=-1, name=None):
    return apply_op("glu", lambda v: jax.nn.glu(v, axis=axis), [x])


@_export
def maxout(x, groups, axis=1, name=None):
    def fn(v):
        shape = list(v.shape)
        c = shape[axis]
        shape[axis : axis + 1] = [c // groups, groups]
        return jnp.max(v.reshape(shape), axis=axis + 1)

    return apply_op("maxout", fn, [x])


@_export
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        n = jnp.linalg.norm(v, ord=p, axis=axis, keepdims=True)
        return v / jnp.maximum(n, epsilon)

    return apply_op("normalize", fn, [x])


@_export
def temperature_scaled_softmax(x, temperature=1.0, axis=-1):
    return softmax(x / temperature if temperature != 1.0 else x, axis=axis)


# ============================ linear / embedding ============================

@_export
def linear(x, weight, bias=None, name=None):
    if bias is None:
        return apply_op("linear", lambda v, w: v @ w, [x, weight])
    return apply_op("linear", lambda v, w, b: v @ w + b, [x, weight, bias])


@_export
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    """activation.py hardsigmoid — clip(slope·x + offset, 0, 1); the
    reference defaults are slope=1/6, offset=0.5."""
    return apply_op("hardsigmoid",
                    lambda v: jnp.clip(v * slope + offset, 0, 1), [x])


@_export
def embedding(x, weight, padding_idx=None, sparse=False, max_norm=None,
              norm_type=2.0, scale_grad_by_freq=False, name=None):
    if scale_grad_by_freq:
        raise NotImplementedError(
            "scale_grad_by_freq: frequency-scaled sparse gradients are a "
            "row-sparse-grad optimization; dense XLA grads make it a no-op "
            "risk — not supported")

    def fn(ids, w):
        out = jnp.take(w, ids, axis=0)
        if max_norm is not None:
            # renorm looked-up vectors whose p-norm exceeds max_norm
            n = jnp.linalg.norm(out.astype(jnp.float32), ord=norm_type,
                                axis=-1, keepdims=True)
            scale_f = jnp.where(n > max_norm, max_norm / jnp.maximum(n, 1e-12),
                                1.0)
            out = (out * scale_f).astype(out.dtype)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply_op("embedding", fn, [x, weight])


@_export
def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _oh

    return _oh(x, num_classes)


@_export
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l):
        k = l.shape[-1]
        u = 1.0 / k if prior_dist is None else _unwrap(prior_dist)
        return (1 - epsilon) * l + epsilon * u

    return apply_op("label_smooth", fn, [label])


@_export
def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    inputs = [x1, x2, weight] + ([bias] if bias is not None else [])
    return apply_op("bilinear", fn, inputs)


# ============================ dropout ============================

@_export
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(_unwrap(x))
    key = rng.next_key()

    def fn(v):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in [a % v.ndim for a in axes] else 1 for i, s in enumerate(v.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return apply_op("dropout", fn, [x])


@_export
def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ch_axis = 1 if data_format == "NCHW" else 3
    return dropout(x, p, axis=[0, ch_axis], training=training)


@_export
def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ch_axis = 1 if data_format == "NCDHW" else 4
    return dropout(x, p, axis=[0, ch_axis], training=training)


def _alpha_dropout(x, p, training, mask_shape_of, op_name):
    """SELU-preserving dropout core (Klambauer et al.): dropped positions take
    alpha' = -alpha*scale, then an affine (a, b) correction restores zero mean
    and unit variance.  ``mask_shape_of`` maps the value shape to the
    bernoulli mask shape (full shape = per-element, [:2]+(1,...) = per-channel)."""
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(_unwrap(x))
    key = rng.next_key()
    alpha_p = -1.6732632423543772 * 1.0507009873554805

    def fn(v):
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape_of(v.shape))
        a = (1.0 / _math.sqrt((1 - p) * (1 + p * alpha_p**2))) if p < 1 else 1.0
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return apply_op(op_name, fn, [x])


@_export
def alpha_dropout(x, p=0.5, training=True, name=None):
    return _alpha_dropout(x, p, training, lambda s: s, "alpha_dropout")


# ============================ convolution ============================

def _conv_nd(v, w, stride, padding, dilation, groups, data_format, ndim):
    if data_format[-1] == "C":  # NHWC-style
        lhs_spec = "N" + "DHW"[3 - ndim :] + "C" if ndim == 3 else ("NHWC" if ndim == 2 else "NWC")
    else:
        lhs_spec = "NC" + "DHW"[3 - ndim :] if ndim == 3 else ("NCHW" if ndim == 2 else "NCW")
    rhs_spec = "OI" + "DHW"[3 - ndim :] if ndim == 3 else ("OIHW" if ndim == 2 else "OIW")
    out_spec = lhs_spec
    if isinstance(padding, str):
        pad_cfg = padding.upper()
    else:
        p = _pair(padding, ndim)
        if len(p) == ndim:
            pad_cfg = [(pi, pi) for pi in p]
        else:  # explicit lo/hi pairs
            pad_cfg = [(p[2 * i], p[2 * i + 1]) for i in range(ndim)]
    return jax.lax.conv_general_dilated(
        v,
        w,
        window_strides=_pair(stride, ndim),
        padding=pad_cfg,
        rhs_dilation=_pair(dilation, ndim),
        dimension_numbers=(lhs_spec, rhs_spec, out_spec),
        feature_group_count=groups,
    )


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, ndim, name):
    def fn(v, w, *rest):
        out = _conv_nd(v, w, stride, padding, dilation, groups, data_format, ndim)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if data_format[1] == "C" else out.ndim - 1] = b.size
            out = out + b.reshape(shape)
        return out

    inputs = [x, weight] + ([bias] if bias is not None else [])
    return apply_op(name, fn, inputs)


@_export
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, "NCW" if data_format == "NCL" else "NWC", 1, "conv1d")


@_export
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2, "conv2d")


@_export
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, ndim, name):
    def fn(v, w, *rest):
        strides = _pair(stride, ndim)
        pads = _pair(padding, ndim)
        dil = _pair(dilation, ndim)
        opad = _pair(output_padding, ndim)
        # weight layout paddle: [in, out//groups, *k]; grad-style transposed conv
        k = w.shape[2:]
        pad_cfg = [
            (dil[i] * (k[i] - 1) - pads[i], dil[i] * (k[i] - 1) - pads[i] + opad[i])
            for i in range(ndim)
        ]
        if data_format[-1] == "C":
            lhs_spec = {1: "NWC", 2: "NHWC", 3: "NDHWC"}[ndim]
        else:
            lhs_spec = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]
        rhs_spec = {1: "IOW", 2: "IOHW", 3: "IODHW"}[ndim]
        if groups > 1:
            w_ = w.reshape((groups, w.shape[0] // groups) + w.shape[1:])
            outs = []
            ch_ax = 1 if data_format[1] == "C" else v.ndim - 1
            vs = jnp.split(v, groups, axis=ch_ax)
            for g in range(groups):
                outs.append(
                    jax.lax.conv_general_dilated(
                        vs[g], w_[g],
                        window_strides=(1,) * ndim,
                        padding=pad_cfg,
                        lhs_dilation=strides,
                        rhs_dilation=dil,
                        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
                    )
                )
            out = jnp.concatenate(outs, axis=ch_ax)
        else:
            out = jax.lax.conv_general_dilated(
                v,
                w,
                window_strides=(1,) * ndim,
                padding=pad_cfg,
                lhs_dilation=strides,
                rhs_dilation=dil,
                dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
            )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if data_format[1] == "C" else out.ndim - 1] = b.size
            out = out + b.reshape(shape)
        return out

    inputs = [x, weight] + ([bias] if bias is not None else [])
    return apply_op(name, fn, inputs)



def _op_from_output_size(x, weight, stride, padding, dilation, output_size,
                         ndim, data_format):
    """output_size → output_padding (common.py conv_transpose contract):
    out = (in-1)·stride - 2·pad + dilation·(k-1) + 1 + output_padding."""
    st = _pair(stride, ndim)
    pd = _pair(padding, ndim)
    dl = _pair(dilation, ndim)
    ks = _unwrap(weight).shape[2:2 + ndim]
    ch_first = data_format[1] == "C"
    sp = (_unwrap(x).shape[2:2 + ndim] if ch_first
          else _unwrap(x).shape[1:1 + ndim])
    want = _pair(output_size, ndim)
    opad = []
    for i in range(ndim):
        base = (sp[i] - 1) * st[i] - 2 * pd[i] + dl[i] * (ks[i] - 1) + 1
        extra = int(want[i]) - base
        if not 0 <= extra < st[i]:
            raise ValueError(
                f"output_size[{i}]={want[i]} unreachable: base {base}, "
                f"stride {st[i]} allows [{base}, {base + st[i] - 1}]")
        opad.append(extra)
    return tuple(opad)


@_export
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    if output_size is not None:
        output_padding = _op_from_output_size(x, weight, stride, padding,
                                              dilation, output_size, 1, "NCL")
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, "NCW", 1, "conv1d_transpose")


@_export
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    if output_size is not None:
        output_padding = _op_from_output_size(x, weight, stride, padding,
                                              dilation, output_size, 2,
                                              data_format)
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, 2, "conv2d_transpose")


@_export
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    if output_size is not None:
        output_padding = _op_from_output_size(x, weight, stride, padding,
                                              dilation, output_size, 3,
                                              data_format)
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, 3, "conv3d_transpose")


# ============================ pooling ============================

def _pool(x, ksize, stride, padding, ndim, data_format, reducer, init, name, count_include_pad=True, ceil_mode=False):
    ks = _pair(ksize, ndim)
    st = _pair(stride if stride is not None else ksize, ndim)
    pd = _pair(padding, ndim)

    def fn(v):
        ch_first = data_format[1] == "C"
        sp_axes = range(2, 2 + ndim) if ch_first else range(1, 1 + ndim)
        # ceil_mode: extend the high side so partial windows emit outputs,
        # with the reference's rule that a window must start inside
        # input+padding (pooling.py ceil-mode contract)
        extra = [0] * ndim
        if ceil_mode:
            for i, ax in enumerate(sp_axes):
                n = v.shape[ax] + 2 * pd[i]
                o = -(-(n - ks[i]) // st[i]) + 1
                if (o - 1) * st[i] >= v.shape[ax] + pd[i]:
                    o -= 1
                extra[i] = max(0, (o - 1) * st[i] + ks[i] - n)
        if ch_first:
            window = (1, 1) + ks
            strides = (1, 1) + st
            pads = ((0, 0), (0, 0)) + tuple(
                (p, p + e) for p, e in zip(pd, extra))
        else:
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
            pads = ((0, 0),) + tuple(
                (p, p + e) for p, e in zip(pd, extra)) + ((0, 0),)
        if reducer == "max":
            neg = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
            return jax.lax.reduce_window(v, neg, jax.lax.max, window, strides, pads)
        s = jax.lax.reduce_window(v.astype(jnp.float32), 0.0, jax.lax.add, window, strides, pads)
        if count_include_pad:
            if not any(extra):
                return (s / float(np.prod(ks))).astype(v.dtype)
            # symmetric padding counts toward the divisor, the ceil-mode
            # extension does not: count over ones that cover input+padding
            sym = [(0, 0)] * v.ndim
            for i, ax in enumerate(sp_axes):
                sym[ax] = (pd[i], pd[i])
            ones = jnp.pad(jnp.ones_like(v, jnp.float32), sym,
                           constant_values=1.0)
            zpads = tuple((0, pads[d][1] - sym[d][1]) for d in range(v.ndim))
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, zpads)
            return (s / cnt).astype(v.dtype)
        ones = jnp.ones_like(v, jnp.float32)
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
        return (s / cnt).astype(v.dtype)

    return apply_op(name, fn, [x])


@_export
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    if return_mask:
        return _max_pool_mask(x, kernel_size, stride, padding, 1,
                              "max_pool1d", ceil_mode)
    return _pool(x, kernel_size, stride, padding, 1, "NCW", "max", None, "max_pool1d", ceil_mode=ceil_mode)


@_export
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_mask(x, kernel_size, stride, padding, 2,
                              "max_pool2d", ceil_mode, data_format)
    return _pool(x, kernel_size, stride, padding, 2, data_format, "max", None, "max_pool2d", ceil_mode=ceil_mode)


@_export
def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_mask(x, kernel_size, stride, padding, 3,
                              "max_pool3d", ceil_mode, data_format)
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max", None, "max_pool3d", ceil_mode=ceil_mode)


@_export
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "NCW", "avg", None, "avg_pool1d", count_include_pad=not exclusive, ceil_mode=ceil_mode)


@_export
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg", None, "avg_pool2d", count_include_pad=not exclusive, ceil_mode=ceil_mode)


@_export
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg", None, "avg_pool3d", count_include_pad=not exclusive, ceil_mode=ceil_mode)


def _adaptive_pool(x, output_size, ndim, data_format, mode, name):
    out_sz = _pair(output_size, ndim)

    def fn(v):
        spatial_start = 2 if data_format[1] == "C" else 1
        out = v
        for i in range(ndim):
            ax = spatial_start + i
            in_s, out_s = out.shape[ax], out_sz[i]
            if out_s == in_s:
                continue
            if in_s % out_s == 0:
                k = in_s // out_s
                new_shape = out.shape[:ax] + (out_s, k) + out.shape[ax + 1 :]
                r = out.reshape(new_shape)
                out = jnp.max(r, axis=ax + 1) if mode == "max" else jnp.mean(r, axis=ax + 1)
            else:
                # generic adaptive: gather variable windows
                starts = (np.arange(out_s) * in_s) // out_s
                ends = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
                pieces = []
                for s, e in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(s), int(e), axis=ax)
                    red = jnp.max(seg, axis=ax, keepdims=True) if mode == "max" else jnp.mean(seg, axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply_op(name, fn, [x])


@_export
def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg", "adaptive_avg_pool1d")


@_export
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg", "adaptive_avg_pool2d")


@_export
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg", "adaptive_avg_pool3d")


def _adaptive_max_mask(x, output_size, ndim, name):
    """Adaptive max pool returning (out, mask): mask is each max's flat
    index in the input's spatial dims (the reference return_mask contract;
    feeds max_unpool).  Variable adaptive windows ride the same
    _windowed_argmax as the strided pools, padded to the widest window."""
    out_sz = _pair(output_size, ndim)

    def fn(v):
        S = v.shape[2:]
        pos, valid = [], []
        for i in range(ndim):
            in_s, o = S[i], out_sz[i]
            starts = (np.arange(o) * in_s) // o
            ends = ((np.arange(o) + 1) * in_s + o - 1) // o
            kmax = int((ends - starts).max())
            p = starts[:, None] + np.arange(kmax)[None, :]
            valid.append(p < ends[:, None])
            pos.append(np.clip(p, 0, in_s - 1))
        return _windowed_argmax(v, pos, valid)

    return apply_op(name, fn, [x], n_outputs=2)


@_export
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_mask(x, output_size, 1, "adaptive_max_pool1d")
    return _adaptive_pool(x, output_size, 1, "NCW", "max", "adaptive_max_pool1d")


@_export
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_mask(x, output_size, 2, "adaptive_max_pool2d")
    return _adaptive_pool(x, output_size, 2, "NCHW", "max", "adaptive_max_pool2d")


@_export
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_mask(x, output_size, 3, "adaptive_max_pool3d")
    return _adaptive_pool(x, output_size, 3, "NCDHW", "max", "adaptive_max_pool3d")


# ============================ normalization ============================

@_export
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))

    def fn(v, *rest):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        mean = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((v.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * rest[i]
            i += 1
        if bias is not None:
            out = out + rest[i]
        return out

    inputs = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("layer_norm", fn, inputs)


@_export
def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    ch_axis = 1 if data_format[1] == "C" else _unwrap(x).ndim - 1

    if training and not use_global_stats:
        # compute batch stats and update running stats in-place (eager semantics)
        def fn(v, *rest):
            axes = tuple(i for i in range(v.ndim) if i != ch_axis)
            m = jnp.mean(v.astype(jnp.float32), axis=axes)
            var = jnp.var(v.astype(jnp.float32), axis=axes)
            shape = [1] * v.ndim
            shape[ch_axis] = v.shape[ch_axis]
            out = (v.astype(jnp.float32) - m.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
            out = out.astype(v.dtype)
            i = 0
            if weight is not None:
                out = out * rest[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + rest[i].reshape(shape)
            return out

        v = _unwrap(x)
        axes = tuple(i for i in range(v.ndim) if i != ch_axis)
        batch_mean = jnp.mean(v.astype(jnp.float32), axis=axes)
        batch_var = jnp.var(v.astype(jnp.float32), axis=axes)
        from ...jit import in_functional_swap

        # tracer-valued updates are allowed only for buffers belonging to an
        # active functional swap (jit.functional_call / TrainStep / DistModel)
        # — those are captured before the swap exits; anywhere else a tracer
        # assignment would permanently corrupt eager state, so skip it
        if running_mean is not None and (
            not isinstance(batch_mean, jax.core.Tracer)
            or (in_functional_swap(running_mean) and in_functional_swap(running_var))
        ):
            rm, rv = _unwrap(running_mean), _unwrap(running_var)
            running_mean._value = (momentum * rm + (1 - momentum) * batch_mean).astype(rm.dtype)
            running_var._value = (momentum * rv + (1 - momentum) * batch_var).astype(rv.dtype)
        inputs = [x] + [t for t in (weight, bias) if t is not None]
        return apply_op("batch_norm", fn, inputs)

    def fn(v, m, var, *rest):
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        out = (v - m.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape).astype(v.dtype) + epsilon)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out

    inputs = [x, running_mean, running_var] + [t for t in (weight, bias) if t is not None]
    return apply_op("batch_norm", fn, inputs)


@_export
def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    ch_axis = 1 if data_format[1] == "C" else _unwrap(x).ndim - 1

    def fn(v, *rest):
        axes = tuple(i for i in range(2, v.ndim)) if ch_axis == 1 else tuple(range(1, v.ndim - 1))
        m = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((v.astype(jnp.float32) - m) * jax.lax.rsqrt(var + eps)).astype(v.dtype)
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        return out

    inputs = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("instance_norm", fn, inputs)


@_export
def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    def fn(v, *rest):
        if data_format[1] != "C":
            v_ = jnp.moveaxis(v, -1, 1)
        else:
            v_ = v
        n, c = v_.shape[:2]
        g = num_groups
        r = v_.reshape((n, g, c // g) + v_.shape[2:])
        axes = tuple(range(2, r.ndim))
        m = jnp.mean(r.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(r.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((r.astype(jnp.float32) - m) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        out = out.reshape(v_.shape)
        shape = [1, c] + [1] * (v_.ndim - 2)
        i = 0
        if weight is not None:
            out = out * rest[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].reshape(shape)
        if data_format[1] != "C":
            out = jnp.moveaxis(out, 1, -1)
        return out

    inputs = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("group_norm", fn, inputs)


@_export
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(v):
        ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
        sq = jnp.square(v)
        half = size // 2
        mv = jnp.moveaxis(sq, ch_axis, -1)
        padded = jnp.pad(mv, [(0, 0)] * (mv.ndim - 1) + [(half, size - half - 1)])
        win = sum(
            jax.lax.slice_in_dim(padded, i, i + mv.shape[-1], axis=mv.ndim - 1)
            for i in range(size)
        )
        div = (k + alpha * win / size) ** beta
        return v / jnp.moveaxis(div, -1, ch_axis)

    return apply_op("local_response_norm", fn, [x])


# ============================ losses ============================

@_export
def mse_loss(input, label, reduction="mean", name=None):
    def fn(a, b):
        d = (a - b) ** 2
        return _reduce_loss(d, reduction)

    return apply_op("mse_loss", fn, [input, label])


def _reduce_loss(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


@_export
def l1_loss(input, label, reduction="mean", name=None):
    return apply_op("l1_loss", lambda a, b: _reduce_loss(jnp.abs(a - b), reduction), [input, label])


@_export
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce_loss(loss, reduction)

    return apply_op("smooth_l1_loss", fn, [input, label])


@_export
def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    inputs = [input, label] + ([weight] if weight is not None else [])

    def fn(logits, lab, *rest):
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label or (lab.ndim == logits.ndim and lab.shape == logits.shape and jnp.issubdtype(lab.dtype, jnp.floating)):
            sl = lab
            if label_smoothing > 0:
                k = logits.shape[axis]
                sl = sl * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(sl * logp, axis=axis)
            valid = None
        else:
            lab_ = lab.squeeze(axis) if (lab.ndim == logits.ndim and lab.shape[axis] == 1) else lab
            k = logits.shape[axis]
            valid = lab_ != ignore_index
            safe = jnp.where(valid, lab_, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis).astype(jnp.int32), axis=axis
            ).squeeze(axis)
            if label_smoothing > 0:
                smooth = jnp.mean(logp, axis=axis)
                picked = (1 - label_smoothing) * picked + label_smoothing * smooth
            loss = jnp.where(valid, -picked, 0.0)
            if rest:  # class weights
                w = rest[0]
                wsel = jnp.where(valid, jnp.take(w, safe), 0.0)
                loss = loss * wsel
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wsel), 1e-12)
        if reduction == "mean":
            if valid is not None:
                return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.mean(loss)
        return _reduce_loss(loss, reduction)

    return apply_op("cross_entropy", fn, inputs)


@_export
def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    from ...ops.manipulation import unsqueeze

    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


@_export
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    inputs = [input, label] + ([weight] if weight is not None else [])

    def fn(logp, lab, *rest):
        valid = lab != ignore_index
        safe = jnp.where(valid, lab, 0)
        picked = jnp.take_along_axis(logp, safe[:, None].astype(jnp.int32), axis=1).squeeze(1)
        loss = jnp.where(valid, -picked, 0.0)
        if rest:
            w = jnp.take(rest[0], safe)
            loss = loss * jnp.where(valid, w, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce_loss(loss, reduction)

    return apply_op("nll_loss", fn, inputs)


@_export
def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    inputs = [input, label] + ([weight] if weight is not None else [])

    def fn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce_loss(loss, reduction)

    return apply_op("binary_cross_entropy", fn, inputs)


@_export
def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    inputs = [logit, label] + [t for t in (weight, pos_weight) if t is not None]

    def fn(z, y, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        # stable bce-with-logits
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce_loss(loss, reduction)

    return apply_op("bce_with_logits", fn, inputs)


@_export
def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            loss = jnp.where(y > 0, y * (jnp.log(jnp.maximum(y, 1e-30)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce_loss(loss, reduction)

    return apply_op("kl_div", fn, [input, label])


@_export
def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply_op("cosine_similarity", fn, [x1, x2])


@_export
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply_op(
        "margin_ranking_loss",
        lambda a, b, y: _reduce_loss(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        [input, other, label],
    )


@_export
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply_op(
        "hinge_embedding_loss",
        lambda a, y: _reduce_loss(jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        [input, label],
    )


@_export
def square_error_cost(input, label, name=None):
    return apply_op("square_error_cost", lambda a, b: (a - b) ** 2, [input, label])


@_export
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    inputs = [logit, label] + ([normalizer] if normalizer is not None else [])

    def fn(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce_loss(loss, reduction)

    return apply_op("sigmoid_focal_loss", fn, inputs)


# ============================ vision helpers ============================

@_export
def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    def fn(v):
        chlast = data_format[-1] == "C"
        v_ = v if chlast else jnp.moveaxis(v, 1, -1)
        spatial = v_.shape[1:-1]
        if size is not None:
            out_sz = _pair(size, len(spatial))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
            out_sz = tuple(int(s * f) for s, f in zip(spatial, sf))
        method = {"nearest": "nearest", "bilinear": "linear", "trilinear": "linear", "bicubic": "cubic", "linear": "linear", "area": "linear"}[mode]
        out = jax.image.resize(v_, (v_.shape[0],) + out_sz + (v_.shape[-1],), method=method)
        return out if chlast else jnp.moveaxis(out, -1, 1)

    return apply_op("interpolate", fn, [x])


@_export
def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


@_export
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            out = v.reshape(n, c // (r * r), r, r, h, w)
            out = out.transpose(0, 1, 4, 2, 5, 3)
            return out.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        out = v.reshape(n, h, w, r, r, c // (r * r))
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h * r, w * r, c // (r * r))

    return apply_op("pixel_shuffle", fn, [x])


@_export
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(v):
        n, c, h, w = v.shape
        out = v.reshape(n, c, h // r, r, w // r, r)
        out = out.transpose(0, 1, 3, 5, 2, 4)
        return out.reshape(n, c * r * r, h // r, w // r)

    return apply_op("pixel_unshuffle", fn, [x])


@_export
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def fn(v, g):
        n, c, h, w = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            ix = (gx + 1) * 0.5 * (w - 1)
            iy = (gy + 1) * 0.5 * (h - 1)
        else:
            ix = ((gx + 1) * w - 1) * 0.5
            iy = ((gy + 1) * h - 1) * 0.5
        ix0 = jnp.floor(ix).astype(jnp.int32)
        iy0 = jnp.floor(iy).astype(jnp.int32)
        ix1, iy1 = ix0 + 1, iy0 + 1
        wx1 = ix - ix0
        wy1 = iy - iy0
        wx0, wy0 = 1 - wx1, 1 - wy1

        def sample(iy_, ix_):
            mask = (ix_ >= 0) & (ix_ < w) & (iy_ >= 0) & (iy_ < h)
            ixc = jnp.clip(ix_, 0, w - 1)
            iyc = jnp.clip(iy_, 0, h - 1)
            out = v[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [n, gh, gw, c]
            return jnp.where(mask[..., None], out, 0.0)

        out = (
            sample(iy0, ix0) * (wy0 * wx0)[..., None]
            + sample(iy0, ix1) * (wy0 * wx1)[..., None]
            + sample(iy1, ix0) * (wy1 * wx0)[..., None]
            + sample(iy1, ix1) * (wy1 * wx1)[..., None]
        )
        return jnp.moveaxis(out, -1, 1)

    return apply_op("grid_sample", fn, [x, grid])


# ============================ attention ============================

@_export
def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None
):
    """Inputs [batch, seq, heads, head_dim] (paddle convention).  Dispatches to the
    Pallas flash-attention kernel on TPU when enabled, else XLA-composed attention."""
    from ...core.flags import flag

    if flag("FLAGS_use_pallas_kernels"):
        try:
            from ...ops.pallas import flash_attention as fa

            inputs = [query, key, value] + ([attn_mask] if attn_mask is not None else [])

            def fn(q, k, v, *rest):
                return fa.flash_attention_bshd(q, k, v, rest[0] if rest else None, is_causal)

            return apply_op("flash_attention", fn, inputs)
        except Exception:
            pass

    inputs = [query, key, value] + ([attn_mask] if attn_mask is not None else [])

    def fn(q, k, v, *rest):
        # single shared core (flash_attention._dense_attention); sdpa keeps
        # the torch/paddle TOP-LEFT causal alignment
        from .flash_attention import _dense_attention

        scale = 1.0 / _math.sqrt(q.shape[-1])
        return _dense_attention(q, k, v, rest[0] if rest else None, is_causal,
                                scale, dropout_p, training, False,
                                causal_align="tl")[0]

    return apply_op("sdpa", fn, inputs)


from .flash_attention import (  # noqa: E402
    calc_reduced_attention_scores,
    flash_attention,
    flash_attn_qkvpacked,
    flash_attn_unpadded,
    flash_attn_varlen_qkvpacked,
    flashmask_attention,
    sdp_kernel,
)
from .sparse_attention import sparse_attention  # noqa: E402

__all__ += [
    "flash_attention", "flash_attn_unpadded", "flashmask_attention",
    "flash_attn_qkvpacked", "flash_attn_varlen_qkvpacked",
    "calc_reduced_attention_scores", "sdp_kernel", "sparse_attention",
]


# sequence mask utility
@_export
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    v = _unwrap(x)
    m = int(maxlen) if maxlen is not None else int(jnp.max(v))
    mask = jnp.arange(m)[None, :] < v[..., None]
    return Tensor(mask.astype(dtypes.convert_dtype(dtype)))


# ============== reference loss tail (python/paddle/nn/functional/loss.py) ====

@_export
def log_loss(input, label, epsilon=1e-4, name=None):
    """loss.py:129: -label*log(input+eps) - (1-label)*log(1-input+eps),
    elementwise (no reduction)."""
    def fn(x, y):
        y = y.astype(x.dtype)
        return -y * jnp.log(x + epsilon) - (1.0 - y) * jnp.log(1.0 - x + epsilon)

    return apply_op("log_loss", fn, [input, label])


@_export
def soft_margin_loss(input, label, reduction="mean", name=None):
    """loss.py:4193: log(1 + exp(-label * input)), label in {-1, 1}."""
    def fn(x, y):
        return _reduce_loss(jnp.log1p(jnp.exp(-y.astype(x.dtype) * x)),
                            reduction)

    return apply_op("soft_margin_loss", fn, [input, label])


@_export
def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """loss.py:4066: hinge between the true-class score and every other."""
    def fn(x, y, *rest):
        n, c = x.shape
        true = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), axis=1)
        m = jnp.maximum(margin - true + x, 0.0) ** p
        if rest:
            m = m * rest[0][y.astype(jnp.int32)][:, None]
        m = m * (1 - jax.nn.one_hot(y, c, dtype=x.dtype))  # exclude true class
        return _reduce_loss(m.sum(-1) / c, reduction)

    ins = [input, label] + ([weight] if weight is not None else [])
    return apply_op("multi_margin_loss", fn, ins)


@_export
def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    """loss.py:3438: per-class binary logistic loss, labels in {0, 1}."""
    def fn(x, y, *rest):
        y = y.astype(x.dtype)
        per = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if rest:
            per = per * rest[0]
        return _reduce_loss(per.mean(-1), reduction)

    ins = [input, label] + ([weight] if weight is not None else [])
    return apply_op("multi_label_soft_margin_loss", fn, ins)


@_export
def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    """loss.py:1563: Poisson negative log likelihood."""
    def fn(x, y):
        y = y.astype(x.dtype)
        if log_input:
            per = jnp.exp(x) - y * x
        else:
            per = x - y * jnp.log(x + epsilon)
        if full:
            # Stirling approximation for label! (only where label > 1)
            stir = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
            per = per + jnp.where(y > 1, stir, 0.0)
        return _reduce_loss(per, reduction)

    return apply_op("poisson_nll_loss", fn, [input, label])


@_export
def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """loss.py: 0.5*(log(var) + (x-label)^2/var), variance clamped."""
    def fn(x, y, var):
        var = jnp.maximum(var.astype(x.dtype), epsilon)
        per = 0.5 * (jnp.log(var) + (x - y.astype(x.dtype)) ** 2 / var)
        if full:
            per = per + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, x.dtype))
        return _reduce_loss(per, reduction)

    return apply_op("gaussian_nll_loss", fn, [input, label, variance])


@_export
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    """loss.py:3660: 1-cos for label=1, max(0, cos - margin) for label=-1."""
    def fn(a, b, y):
        cos = (a * b).sum(-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        per = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce_loss(per, reduction)

    return apply_op("cosine_embedding_loss", fn, [input1, input2, label])


@_export
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    """loss.py:3936: max(d(a,p) - d(a,n) + margin, 0)."""
    def fn(a, pos, neg):
        def dist(u, v):
            return ((jnp.abs(u - v) + epsilon) ** p).sum(-1) ** (1.0 / p)

        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_an = jnp.minimum(d_an, dist(pos, neg))
        return _reduce_loss(jnp.maximum(d_ap - d_an + margin, 0.0), reduction)

    return apply_op("triplet_margin_loss", fn, [input, positive, negative])


@_export
def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """loss.py triplet_margin_with_distance_loss: triplet hinge with a
    caller-supplied distance (default pairwise L2)."""
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    d_ap = dist(input, positive)
    d_an = dist(input, negative)
    if swap:
        from ...ops import math as _m
        d_an = _m.minimum(d_an, dist(positive, negative))

    def fn(ap, an):
        return _reduce_loss(jnp.maximum(ap - an + margin, 0.0), reduction)

    return apply_op("triplet_margin_with_distance_loss", fn, [d_ap, d_an])


@_export
def dice_loss(input, label, epsilon=1e-5, name=None):
    """loss.py:50: 1 - 2*intersection/total over one-hot labels."""
    def fn(x, y):
        d = x.shape[-1]
        oh = jax.nn.one_hot(y[..., 0], d, dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = (x * oh).sum(red)
        total = x.sum(red) + oh.sum(red)
        return (1 - (2 * inter + epsilon) / (total + epsilon)).mean()

    return apply_op("dice_loss", fn, [input, label])


@_export
def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """loss.py:346: cross entropy on the anchor x positive similarity matrix
    (both directions) + L2 regularizer on the embeddings."""
    def fn(a, p, y):
        y = y.reshape(-1)
        sim = a @ p.T                                  # [n, n]
        tgt = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = tgt / tgt.sum(-1, keepdims=True)
        xe_r = -(jax.nn.log_softmax(sim, axis=-1) * tgt).sum(-1).mean()
        xe_c = -(jax.nn.log_softmax(sim.T, axis=-1) * tgt).sum(-1).mean()
        l2 = (jnp.sum(a * a) + jnp.sum(p * p)) / a.shape[0]
        return (xe_r + xe_c) / 2 + l2_reg * l2 * 0.25

    return apply_op("npair_loss", fn, [anchor, positive, labels])


@_export
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC loss (loss.py:1906; the warp-ctc alpha recursion as a lax.scan).

    log_probs [T, B, C] (softmax applied internally, like warp-ctc);
    labels [B, U] int; the extended sequence interleaves blanks
    (length 2U+1) and the forward variable alpha runs the standard
    three-way recursion in log space, frozen past each sequence's
    input_length."""
    def fn(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        U = lab.shape[1]
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        S = 2 * U + 1
        ninf = jnp.float32(-1e30)
        # extended labels: even slots blank, odd slots the labels
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        # repeat rule: s can skip from s-2 unless same label or blank
        ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, jnp.int32),
                                  ext[:, :-2]], axis=1)
        can_skip = (ext != blank) & (ext != ext_m2)         # [B, S]
        pos = jnp.arange(S)[None, :]
        valid_s = pos < (2 * lab_len[:, None] + 1)          # live slots

        def emit(t_lp, a):
            # a [B, S] -> next alpha at time t
            a1 = jnp.concatenate([jnp.full((B, 1), ninf), a[:, :-1]], axis=1)
            a2 = jnp.concatenate([jnp.full((B, 2), ninf), a[:, :-2]], axis=1)
            a2 = jnp.where(can_skip, a2, ninf)
            tot = jnp.logaddexp(jnp.logaddexp(a, a1), a2)
            e = jnp.take_along_axis(t_lp, ext, axis=1)      # [B, S]
            return jnp.where(valid_s, tot + e, ninf)

        alpha0 = jnp.full((B, S), ninf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, first, ninf))

        def step(carry, t):
            a = carry
            nxt = emit(lp[t], a)
            a = jnp.where((t < in_len)[:, None], nxt, a)    # freeze past T_b
            return a, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        end = 2 * lab_len.astype(jnp.int32)                 # [B] blank slot
        last_b = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
        last_l = jnp.take_along_axis(
            alpha, jnp.maximum(end - 1, 0)[:, None], axis=1)[:, 0]
        last_l = jnp.where(lab_len > 0, last_l, ninf)
        loss = -jnp.logaddexp(last_b, last_l)               # [B]
        if norm_by_times:
            # gradient normalized by each sequence's length, value unchanged
            # (warp-ctc's norm_by_times; moot under 'mean' per the docs)
            inv_t = 1.0 / jnp.maximum(in_len.astype(loss.dtype), 1)
            loss = loss * inv_t + jax.lax.stop_gradient(loss * (1 - inv_t))
        if reduction == "mean":
            return (loss / jnp.maximum(lab_len.astype(loss.dtype), 1)).mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply_op("ctc_loss", fn,
                    [log_probs, labels, input_lengths, label_lengths])


@_export
def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss (loss.py:2054; warp-transducer's forward DP as
    a lax.scan over time carrying the alpha row over label positions).

    input [B, T, U+1, C] log-probs (log_softmax applied internally);
    loss_b = -alpha[T_b-1, U_b] - lp[T_b-1, U_b, blank].  FastEmit
    (arxiv 2010.11148, warp-transducer semantics): the LOSS VALUE is the
    exact NLL; the EMIT-path gradient is scaled by (1 + lambda) via a
    stop_gradient identity on the emit log-probs."""
    def fn(lp, lab, in_len, lab_len):
        B, T, U1, C = lp.shape
        U = U1 - 1
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        ninf = jnp.float32(-1e30)
        upos = jnp.arange(U1)[None, :]                      # [1, U+1]
        # per-(b, t, u): blank prob and emit prob of label u (consumed to u+1)
        blank_lp = lp[:, :, :, blank]                       # [B, T, U+1]
        lab_pad = jnp.concatenate(
            [lab.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1)
        emit_lp = jnp.take_along_axis(
            lp, lab_pad[:, None, :, None], axis=3)[..., 0]  # [B, T, U+1]
        if fastemit_lambda:
            # value-preserving gradient scale: a + l*(a - sg(a)) == a in
            # value, d/da == 1 + l — exactly FastEmit's emit-grad scaling
            emit_lp = emit_lp + fastemit_lambda * (
                emit_lp - jax.lax.stop_gradient(emit_lp))

        def time_step(a_prev, t):
            # horizontal (blank) move from t-1 at same u
            horiz = a_prev + blank_lp[:, t - 1]             # [B, U+1]

            # alpha[t, u] = logaddexp(horiz[u], alpha[t, u-1] + emit[t, u-1])
            def chain(carry, inputs):
                h_u, e_um1 = inputs
                cur = jnp.logaddexp(h_u, carry + e_um1)
                return cur, cur

            init = horiz[:, 0]                              # u=0: blank only
            _, rest = jax.lax.scan(
                chain, init,
                (horiz[:, 1:].T, emit_lp[:, t, :-1].T))
            a_t = jnp.concatenate([init[:, None], rest.T], axis=1)
            a_t = jnp.where(upos <= lab_len[:, None], a_t, ninf)
            return jnp.where((t < in_len)[:, None], a_t, a_prev), None

        # t = 0 row: only emits along u
        def chain0(carry, e):
            cur = carry + e
            return cur, cur

        _, r0 = jax.lax.scan(chain0, jnp.zeros((B,), jnp.float32),
                             emit_lp[:, 0, :-1].T)
        a0 = jnp.concatenate([jnp.zeros((B, 1), jnp.float32), r0.T], axis=1)
        a0 = jnp.where(upos <= lab_len[:, None], a0, ninf)

        alpha, _ = jax.lax.scan(time_step, a0, jnp.arange(1, T))
        # final: the frozen carry IS row T_b-1; read it at u = U_b and add
        # the final blank emission
        final_blank = jnp.take_along_axis(
            blank_lp[jnp.arange(B), jnp.maximum(in_len.astype(jnp.int32) - 1, 0)],
            lab_len.astype(jnp.int32)[:, None], axis=1)[:, 0]
        a_end = jnp.take_along_axis(
            alpha, lab_len.astype(jnp.int32)[:, None], axis=1)[:, 0]
        loss = -(a_end + final_blank)
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply_op("rnnt_loss", fn,
                    [input, label, input_lengths, label_lengths])


# ====== reference vision/misc tail (nn/functional/{vision,common,pooling}) ===

@_export
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """distance.py: ||x - y + eps||_p along the last axis."""
    def fn(a, b):
        d = a - b + epsilon
        return (jnp.abs(d) ** p).sum(-1, keepdims=keepdim) ** (1.0 / p)

    return apply_op("pairwise_distance", fn, [x, y])


@_export
def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """vision.py: interleave channel groups (ShuffleNet)."""
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            return (v.reshape(n, groups, c // groups, h, w)
                    .swapaxes(1, 2).reshape(n, c, h, w))
        n, h, w, c = v.shape
        return (v.reshape(n, h, w, groups, c // groups)
                .swapaxes(3, 4).reshape(n, h, w, c))

    return apply_op("channel_shuffle", fn, [x])


@_export
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """vision.py temporal_shift (TSM): shift 1/ratio of channels one segment
    forward/backward along the time axis."""
    def fn(v):
        if data_format != "NCHW":
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v5 = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.concatenate(
            [v5[:, 1:, :c1], jnp.zeros_like(v5[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v5[:, :1, c1:c2]), v5[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([back, fwd, v5[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format != "NCHW":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op("temporal_shift", fn, [x])


@_export
def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    """pooling.py lp_pool2d: (sum of p-th powers over the window)^(1/p)."""
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks
    pd = _pair(padding)

    def fn(v):
        if data_format != "NCHW":
            v = jnp.transpose(v, (0, 3, 1, 2))
        p = float(norm_type)
        hi = [pd[0], pd[1]]
        if ceil_mode:
            # extra high-side padding so partial windows produce outputs
            # (zero-padded x^p contributes nothing to the sum)
            for d in (0, 1):
                n = v.shape[2 + d] + 2 * pd[d]
                out_ceil = -(-(n - ks[d]) // st[d]) + 1
                hi[d] = pd[d] + max(0, (out_ceil - 1) * st[d] + ks[d] - n)
        # plain powf like the reference kernel (pooling.h:84): XLA pow has
        # C powf semantics, so odd norm types keep sign and net-negative
        # windows go NaN at the 1/p root exactly as the reference does
        s = jax.lax.reduce_window(
            v ** p, 0.0, jax.lax.add, (1, 1) + ks, (1, 1) + st,
            [(0, 0), (0, 0), (pd[0], hi[0]), (pd[1], hi[1])])
        out = s ** (1.0 / p)
        if data_format != "NCHW":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op("lp_pool2d", fn, [x])


@_export
def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    """activation.py rrelu: random leaky slope in [lower, upper] when
    training, the midpoint slope in eval (the reference's inference mode)."""
    def fn(v):
        if training:
            key = rng.next_key()
            a = jax.random.uniform(key, v.shape, jnp.float32,
                                   lower, upper).astype(v.dtype)
        else:
            a = jnp.asarray((lower + upper) / 2.0, v.dtype)
        return jnp.where(v >= 0, v, a * v)

    return apply_op("rrelu", fn, [x])


@_export
def affine_grid(theta, out_shape, align_corners=True, name=None):
    """vision.py affine_grid: sampling grid [N, H, W, 2] from a batch of
    2x3 affine matrices (grid_sample's companion)."""
    n, _, h, w = [int(d) for d in out_shape]

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    def fn(th):
        ys = axis_coords(h)
        xs = axis_coords(w)
        gx, gy = jnp.meshgrid(xs, ys)                     # [h, w]
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [h, w, 3]
        return jnp.einsum("hwk,nck->nhwc", base.astype(th.dtype), th)

    return apply_op("affine_grid", fn, [theta])


@_export
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """common.py fold (col2im — unfold's inverse, overlaps summed)."""
    out_hw = _pair(output_sizes)
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = _pair(paddings)
    dl = _pair(dilations)

    def fn(v):
        n, ckk, l = v.shape
        c = ckk // (ks[0] * ks[1])
        lh = (out_hw[0] + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        lw = (out_hw[1] + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        v6 = v.reshape(n, c, ks[0], ks[1], lh, lw)
        H = out_hw[0] + 2 * pd[0]
        W = out_hw[1] + 2 * pd[1]
        out = jnp.zeros((n, c, H, W), v.dtype)
        # scatter-add each kernel tap's grid of patches
        oh = jnp.arange(lh) * st[0]
        ow = jnp.arange(lw) * st[1]
        for i in range(ks[0]):
            for j in range(ks[1]):
                rows = oh + i * dl[0]
                cols = ow + j * dl[1]
                out = out.at[:, :, rows[:, None], cols[None, :]].add(
                    v6[:, :, i, j])
        return out[:, :, pd[0]:H - pd[0] or None, pd[1]:W - pd[1] or None]

    return apply_op("fold", fn, [x])


@_export
def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """pooling.py fractional_max_pool2d (Graham, arXiv:1412.6071).

    Default (kernel_size=None) is the reference's DISJOINT mode: variable
    windows [ceil(a*(i+u)-1), ceil(a*(i+1+u)-1)) with a = n/out, which
    tile the input exactly (pooling.py:2108 example reproduced in tests).
    With kernel_size set, fixed windows start at the same pseudo-random
    positions (overlapping mode).  Deterministic given ``random_u``;
    return_mask yields flat-spatial argmax indices like max_pool2d."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 2, "fractional_max_pool2d")


@_export
def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """common.py class_center_sample (PLSC partial-fc): sample the positive
    class centers plus random negatives up to num_samples; returns
    (remapped_label, sampled_class_index).  Host-side sampling (the sampled
    set is data-dependent by design — the reference's GPU kernel also
    produces variable content in a fixed-size buffer)."""
    lab = np.asarray(_unwrap(label)).astype(np.int64)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos  # every positive center is always kept (reference)
    else:
        rest = np.setdiff1d(np.arange(num_classes, dtype=np.int64), pos)
        seed = int(jax.random.randint(rng.next_key(), (), 0, 2 ** 31 - 1))
        extra = np.random.RandomState(seed).permutation(rest)[
            : num_samples - len(pos)]
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = np.full(num_classes, -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lab])),
            Tensor(jnp.asarray(sampled)))


@_export
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """loss.py:2223 (ArcFace family): softmax CE with the true-class logit
    cos(theta) replaced by cos(m1*theta + m2) - m3, all scaled by s.
    Covers SphereFace (m1), ArcFace (m2), CosFace (m3)."""
    def fn(lg, y):
        n, c = lg.shape
        cos = jnp.clip(lg.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(cos)
        mod = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(y, c, dtype=jnp.float32)
        out = scale * (oh * mod + (1 - oh) * cos)
        logp = jax.nn.log_softmax(out, axis=-1)
        per = -(oh * logp).sum(-1)
        sm = jnp.exp(logp)
        loss = (per.mean() if reduction == "mean"
                else per.sum() if reduction == "sum" else per)
        return loss, sm

    loss, sm = apply_op("margin_cross_entropy", fn, [logits, label],
                        n_outputs=2)
    if return_softmax:
        return loss, sm
    return loss


# ==================== pooling-with-indices / unpooling ====================

def _windowed_argmax(v, pos, valid):
    """Shared core for every pool-with-indices variant: gather variable
    windows described by per-dim ``pos``/``valid`` [out_i, k_i] tables from an
    NC* tensor and return (window max, flat-spatial argmax indices).

    Mirrors the reference max_pool*(return_mask=True) semantics
    (pooling.py:750+): indices address the flattened *input* spatial volume
    per (n, c) plane; invalid (padding) positions are -inf so they are never
    selected."""
    ndim = len(pos)
    S = v.shape[2:]
    out_sizes = [p.shape[0] for p in pos]
    ks = [p.shape[1] for p in pos]
    out = v
    for i in range(ndim):
        ax = 2 + 2 * i  # spatial dim i, after earlier dims became (o, k) pairs
        out = jnp.take(out, jnp.asarray(pos[i].reshape(-1)), axis=ax)
        out = out.reshape(out.shape[:ax] + (out_sizes[i], ks[i]) + out.shape[ax + 1:])
    mask = None
    for i, vd in enumerate(valid):
        shape = [1] * (2 * ndim)
        shape[2 * i], shape[2 * i + 1] = vd.shape
        mm = jnp.asarray(vd).reshape(shape)
        mask = mm if mask is None else (mask & mm)
    neg = (jnp.iinfo(v.dtype).min if jnp.issubdtype(v.dtype, jnp.integer)
           else jnp.asarray(-jnp.inf, v.dtype))
    patches = jnp.where(mask[None, None], out, neg)
    perm = ([0, 1] + [2 + 2 * i for i in range(ndim)]
            + [3 + 2 * i for i in range(ndim)])
    patches = jnp.transpose(patches, perm)
    flat = patches.reshape(patches.shape[:2 + ndim] + (-1,))
    arg = jnp.argmax(flat, axis=-1)            # [n, c, *out] in k-space
    vals = jnp.max(flat, axis=-1)
    # k-space argmax -> global input coords -> row-major flat spatial index
    rem, flat_idx = arg, 0
    for i in range(ndim):
        stride_k = int(np.prod(ks[i + 1:])) if i + 1 < ndim else 1
        ki = rem // stride_k
        rem = rem % stride_k
        o_idx = jnp.arange(out_sizes[i]).reshape(
            [1] * (2 + i) + [out_sizes[i]] + [1] * (ndim - 1 - i))
        coord = jnp.asarray(pos[i])[o_idx, ki]
        flat_idx = flat_idx + coord * (int(np.prod(S[i + 1:])) if i + 1 < ndim else 1)
    return vals, flat_idx.astype(jnp.int32)


def _max_pool_mask(x, kernel_size, stride, padding, ndim, op_name,
                   ceil_mode=False, data_format=None):
    if data_format is not None and data_format[-1] == "C":
        raise ValueError(
            f"{op_name}: return_mask=True only supports channels-first "
            f"data_format, got {data_format} (matches the reference, "
            "pooling.py:1215)")
    ks = _pair(kernel_size, ndim)
    st = _pair(stride if stride is not None else kernel_size, ndim)
    pd = _pair(padding, ndim)

    def fn(v):
        S = v.shape[2:]
        pos, valid = [], []
        for i in range(ndim):
            n = S[i] + 2 * pd[i]
            if ceil_mode:
                o = -(-(n - ks[i]) // st[i]) + 1
                # ceil-mode windows must start inside input+padding
                if (o - 1) * st[i] >= S[i] + pd[i]:
                    o -= 1
            else:
                o = (n - ks[i]) // st[i] + 1
            p = (np.arange(o)[:, None] * st[i] - pd[i]
                 + np.arange(ks[i])[None, :])
            valid.append((p >= 0) & (p < S[i]))
            pos.append(np.clip(p, 0, S[i] - 1))
        return _windowed_argmax(v, pos, valid)

    return apply_op(op_name, fn, [x], n_outputs=2)


def _max_unpool(x, indices, kernel_size, stride, padding, ndim, output_size,
                op_name, data_format=None):
    """Scatter pooled values back to argmax positions (reference
    pooling.py:750/873/1005 max_unpool1d/2d/3d)."""
    if data_format is not None and data_format[-1] == "C":
        raise ValueError(
            f"{op_name}: only channels-first data_format is supported, "
            f"got {data_format} (matches the reference, pooling.py:750+)")
    ks = _pair(kernel_size, ndim)
    st = _pair(stride if stride is not None else kernel_size, ndim)
    pd = _pair(padding, ndim)
    in_sp = tuple(int(s) for s in x.shape[2:])
    if output_size is None:
        out_sp = tuple((in_sp[i] - 1) * st[i] - 2 * pd[i] + ks[i]
                       for i in range(ndim))
    else:
        out_sp = tuple(int(s) for s in tuple(output_size)[-ndim:])

    def fn(v, idx):
        n, c = v.shape[:2]
        flat_v = v.reshape(n, c, -1)
        flat_i = idx.reshape(n, c, -1).astype(jnp.int32)
        res = jnp.zeros((n, c, int(np.prod(out_sp))), v.dtype)
        bi = jnp.arange(n)[:, None, None]
        ci = jnp.arange(c)[None, :, None]
        res = res.at[bi, ci, flat_i].set(flat_v, mode="drop")
        return res.reshape((n, c) + out_sp)

    return apply_op(op_name, fn, [x, indices])


@_export
def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 1,
                       output_size, "max_unpool1d", data_format)


@_export
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 2,
                       output_size, "max_unpool2d", data_format)


@_export
def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 3,
                       output_size, "max_unpool3d", data_format)


@_export
def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """pooling.py:2403 lp_pool1d: p-norm pooling over the length axis."""
    k = _pair(kernel_size, 1)[0]
    s = _pair(stride, 1)[0] if stride is not None else k
    p0 = _pair(padding, 1)[0]

    def fn(v):
        if data_format == "NLC":
            v = jnp.transpose(v, (0, 2, 1))
        p = float(norm_type)
        hi = p0
        if ceil_mode:
            n = v.shape[2] + 2 * p0
            out_ceil = -(-(n - k) // s) + 1
            hi = p0 + max(0, (out_ceil - 1) * s + k - n)
        # plain powf like the reference kernel (pooling.h:84) — see lp_pool2d
        acc = jax.lax.reduce_window(
            v.astype(jnp.float32) ** p, 0.0, jax.lax.add,
            (1, 1, k), (1, 1, s), [(0, 0), (0, 0), (p0, hi)])
        out = (acc ** (1.0 / p)).astype(v.dtype)
        if data_format == "NLC":
            out = jnp.transpose(out, (0, 2, 1))
        return out

    return apply_op("lp_pool1d", fn, [x])


def _fractional_pool_tables(sp, out_sz, kernel_size, random_u, ndim, op_name):
    """Per-dim pos/valid [out, kmax] window tables for fractional pooling
    (Graham, arXiv:1412.6071).  Default (kernel_size=None) is the reference's
    DISJOINT mode: variable windows [ceil(a*(i+u)-1), ceil(a*(i+1+u)-1)) with
    a = n/out, which tile the input exactly; with kernel_size set, fixed
    windows start at the same pseudo-random positions."""
    for d in range(ndim):
        if out_sz[d] > sp[d]:
            raise ValueError(
                f"{op_name}: output_size {tuple(out_sz)} exceeds input "
                f"spatial size {tuple(sp)} (fractional pooling downsamples)")
    u = (float(random_u) if random_u is not None
         else float(jax.random.uniform(rng.next_key(), ())))
    ksz = _pair(kernel_size, ndim) if kernel_size is not None else None

    def bounds(n, o):
        a = n / o
        i = np.arange(o, dtype=np.float64)
        start = np.ceil(a * (i + u) - 1).astype(np.int64)
        end = np.ceil(a * (i + 1 + u) - 1).astype(np.int64)
        return np.clip(start, 0, n - 1), np.clip(end, 1, n)

    pos, valid = [], []
    for d in range(ndim):
        s_, e_ = bounds(sp[d], out_sz[d])
        if ksz is not None:
            s_ = np.clip(s_, 0, sp[d] - ksz[d])
            e_ = s_ + ksz[d]
        kmax = int((e_ - s_).max())
        pos.append(np.minimum(s_[:, None] + np.arange(kmax)[None, :],
                              sp[d] - 1))
        valid.append(np.arange(kmax)[None, :] < (e_ - s_)[:, None])
    return pos, valid


def _fractional_max_pool(x, output_size, kernel_size, random_u, return_mask,
                         ndim, op_name):
    out_sz = _pair(output_size, ndim)

    def fn(v):
        pos, valid = _fractional_pool_tables(
            v.shape[2:], out_sz, kernel_size, random_u, ndim, op_name)
        vals, idx = _windowed_argmax(v, pos, valid)
        return (vals, idx) if return_mask else vals

    return apply_op(op_name, fn, [x], n_outputs=2 if return_mask else 1)


@_export
def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """pooling.py fractional_max_pool3d — the 2d scheme over (D, H, W);
    return_mask yields flat-spatial argmax indices like max_pool3d."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 3, "fractional_max_pool3d")


# ==================== padding / dropout tail ====================

@_export
def zeropad2d(x, padding, data_format="NCHW", name=None):
    """common.py:2068 — zero-pad H/W by [left, right, top, bottom]; thin
    wrapper over the shared constant-pad path (ops/manipulation.py pad)."""
    if isinstance(padding, int):
        padding = [padding] * 4
    return pad(x, list(padding), mode="constant", value=0.0,
               data_format=data_format)


@_export
def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """common.py:1646 — alpha dropout zeroing whole channel maps (the
    SELU-preserving variant of dropout2d/3d)."""
    return _alpha_dropout(x, p, training,
                          lambda s: s[:2] + (1,) * (len(s) - 2),
                          "feature_alpha_dropout")


# ==================== hierarchical sigmoid ====================

@_export
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """loss.py:926 hierarchical sigmoid loss.

    Default tree follows the reference's SimpleCode
    (phi/kernels/funcs/matrix_bit_code.h:100): class c encodes as
    c + num_classes in a 1-rooted heap; classifier index at bit b is
    (code >> (b+1)) - 1 and the target bit is (code >> b) & 1.  Custom
    trees pass explicit path_table / path_code (negative entries pad).
    """
    use_custom = path_table is not None and path_code is not None
    if not use_custom and (num_classes is None or num_classes < 2):
        raise ValueError("hsigmoid_loss: num_classes must be >= 2 for the "
                         "default tree")
    inputs = [input, label, weight] + ([bias] if bias is not None else []) \
        + ([path_table, path_code] if use_custom else [])

    def fn(xv, yv, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if bias is not None else None
        if use_custom:
            tbl, code = rest
            tbl = tbl.astype(jnp.int32)
            bits = code.astype(jnp.int32)
            valid = tbl >= 0
            idx = jnp.where(valid, tbl, 0)
        else:
            y = yv.reshape(-1).astype(jnp.int32) + jnp.int32(num_classes)
            L = int(2 * num_classes - 1).bit_length() - 1  # max path length
            b_r = jnp.arange(L, dtype=jnp.int32)[None, :]
            length = jnp.floor(
                jnp.log2(y.astype(jnp.float32))).astype(jnp.int32)[:, None]
            valid = b_r < length
            idx = jnp.where(valid, (y[:, None] >> (b_r + 1)) - 1, 0)
            bits = (y[:, None] >> b_r) & 1
        logits = jnp.take(wv, idx, axis=0) @ xv[..., None]  # [N, L, 1]
        logits = logits[..., 0]
        if bv is not None:
            logits = logits + jnp.take(bv.reshape(-1), idx)
        # BCE-with-logits, summed over the path
        per_bit = jax.nn.softplus(logits) - bits.astype(logits.dtype) * logits
        loss = jnp.where(valid, per_bit, 0.0).sum(-1, keepdims=True)
        return loss.astype(xv.dtype)

    return apply_op("hsigmoid_loss", fn, inputs)


# ==================== in-place activation aliases ====================
# JAX arrays are immutable; the reference's x.relu_() contract is "result
# lands in x and is returned".  Functional rebinding (the tensor in-place
# machinery in _compat_tail) preserves that contract under the tape; the
# _snapshot() call breaks the would-be tape self-cycle so gradients still
# flow to upstream producers (see Tensor._snapshot).

def _make_act_inplace(base):
    def fn_(x, *args, **kw):
        from ..._compat_tail import _make_inplace

        return _make_inplace(base, fn_.__name__)(x, *args, **kw)

    fn_.__name__ = base.__name__ + "_"
    fn_.__doc__ = f"In-place variant of ``{base.__name__}``."
    __all__.append(fn_.__name__)
    return fn_


elu_ = _make_act_inplace(elu)
hardtanh_ = _make_act_inplace(hardtanh)
leaky_relu_ = _make_act_inplace(leaky_relu)
relu_ = _make_act_inplace(relu)
softmax_ = _make_act_inplace(softmax)
tanh_ = _make_act_inplace(tanh)
thresholded_relu_ = _make_act_inplace(thresholded_relu)


@_export
def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """loss.py:4458 adaptive softmax (Grave et al.).  The reference gathers
    per-cluster row subsets with nonzero(); here every cluster projection is
    computed masked over the full batch — identical math, static shapes
    (XLA-friendly; tail clusters are small by construction)."""
    cutoffs = [int(c) for c in cutoffs]
    n_classes = cutoffs[-1]
    shortlist = cutoffs[0]
    n_clusters = len(cutoffs) - 1
    tail_flat = [w for pair in tail_weights for w in pair]
    inputs = ([input, label, head_weight]
              + ([head_bias] if head_bias is not None else []) + tail_flat)

    lab = _unwrap(label)
    if not isinstance(lab, jax.core.Tracer):  # concrete labels only: the
        lab_np = np.asarray(lab)              # check cannot raise under jit
        if lab_np.size and (lab_np.min() < 0 or lab_np.max() >= n_classes):
            raise ValueError(
                f"label values should be in [0, {n_classes - 1}], but values "
                f"in range [{lab_np.min()}, {lab_np.max()}] were found. ")

    def fn(xv, yv, hw, *rest):
        rest = list(rest)
        hb = rest.pop(0) if head_bias is not None else None
        pairs = [(rest[2 * i], rest[2 * i + 1]) for i in range(n_clusters)]
        squeeze = yv.ndim == 0
        if squeeze:
            xv, yv = xv[None], yv[None]
        y = yv.astype(jnp.int32)
        head = xv @ hw + (hb if hb is not None else 0.0)
        head_lp = jax.nn.log_softmax(head, axis=1)
        gather = jnp.where(y < shortlist, y, 0)
        out = jnp.zeros(y.shape, xv.dtype)
        for i in range(n_clusters):
            low, high = cutoffs[i], cutoffs[i + 1]
            mask = (y >= low) & (y < high)
            rel = jnp.clip(y - low, 0, high - low - 1)
            h = (xv @ pairs[i][0]) @ pairs[i][1]
            clp = jax.nn.log_softmax(h, axis=1)
            local = jnp.take_along_axis(clp, rel[:, None], axis=1)[:, 0]
            out = out + jnp.where(mask, local, 0.0)
            gather = jnp.where(mask, shortlist + i, gather)
        out = out + jnp.take_along_axis(head_lp, gather[:, None], axis=1)[:, 0]
        loss = -out.mean()
        if squeeze:
            out = out[0]
        return out, loss

    return apply_op("adaptive_log_softmax_with_loss", fn, inputs, n_outputs=2)


@_export
def gather_tree(ids, parents):
    """extension.py:149 gather_tree: back-trace beam-search parent pointers
    so every [time, batch, beam] column holds a full candidate sequence."""
    def fn(idv, par):
        k = idv.shape[2]
        init = jnp.tile(jnp.arange(k, dtype=par.dtype)[None, :],
                        (idv.shape[1], 1))

        def step(beams, x):
            step_ids, step_par = x
            out = jnp.take_along_axis(step_ids, beams, axis=1)
            return jnp.take_along_axis(step_par, beams, axis=1), out

        _, outs = jax.lax.scan(step, init,
                               (jnp.flip(idv, 0), jnp.flip(par, 0)))
        return jnp.flip(outs, 0)

    return apply_op("gather_tree", fn, [ids, parents])
