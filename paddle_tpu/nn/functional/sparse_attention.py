"""CSR-masked attention (reference:
python/paddle/nn/functional/sparse_attention.py:22 — a CUDA-11.3 sparse
kernel there; on TPU the CSR layout is expanded to a boolean mask and the
computation stays a dense fused attention, which is how the MXU wants it:
the win of the reference kernel is memory, and XLA gets that back by fusing
the mask into the softmax instead of materializing scores)."""

from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp

from ...core.tensor import apply_op

__all__ = ["sparse_attention"]


def _csr_to_mask(offset, columns, seq_len):
    """offset [S+1], columns [nnz] (one (b,h) slice) → bool [S, S]."""
    nnz = columns.shape[0]
    n = jnp.arange(nnz)
    # row of the n-th nonzero = how many row-starts are <= n, minus 1
    rows = jnp.searchsorted(offset, n, side="right") - 1
    valid = n < offset[-1]
    rows = jnp.clip(rows, 0, seq_len - 1)
    cols = jnp.clip(columns, 0, seq_len - 1)
    mask = jnp.zeros((seq_len, seq_len), bool)
    # .max, not .set: padded entries (valid=False) land on clipped indices
    # that may collide with real nonzeros, and duplicate-index set order is
    # unspecified — max() makes a True win regardless of order
    return mask.at[rows, cols].max(valid)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Inputs [B, H, S, D] (torch layout, matching the reference op); the CSR
    (offset, columns) pair marks which (row, col) score entries participate
    in the softmax.  key_padding_mask [B, S] and attn_mask [S, S] use 0 =
    masked, like the reference."""
    inputs = [query, key, value, sparse_csr_offset, sparse_csr_columns]
    n_extra = 0
    if key_padding_mask is not None:
        inputs.append(key_padding_mask)
        n_extra += 1
    if attn_mask is not None:
        inputs.append(attn_mask)

    def fn(q, k, v, off, cols, *rest):
        from .flash_attention import _dense_attention

        s = q.shape[2]
        mask = jax.vmap(jax.vmap(lambda o, c: _csr_to_mask(o, c, s)))(off, cols)
        i = 0
        if key_padding_mask is not None:
            kp = rest[i]; i += 1
            mask &= (kp != 0)[:, None, None, :]
        if attn_mask is not None:
            mask &= (rest[i] != 0)[None, None, :, :]
        scale = 1.0 / _math.sqrt(q.shape[-1])
        # shared core works on [B, S, H, D]; this op's contract is [B, H, S, D]
        out = _dense_attention(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                               jnp.swapaxes(v, 1, 2), mask, False, scale,
                               0.0, False, False)[0]
        return jnp.swapaxes(out, 1, 2)

    return apply_op("sparse_attention", fn, inputs)
