"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer_base import Layer

__all__ = [
    "BatchNorm",
    "BatchNorm1D",
    "BatchNorm2D",
    "BatchNorm3D",
    "SyncBatchNorm",
    "LayerNorm",
    "GroupNorm",
    "InstanceNorm1D",
    "InstanceNorm2D",
    "InstanceNorm3D",
    "RMSNorm",
    "LocalResponseNorm",
    "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(
        self,
        num_features,
        momentum=0.9,
        epsilon=1e-05,
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
        use_global_stats=None,
        name=None,
    ):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x,
            self._mean,
            self._variance,
            self.weight,
            self.bias,
            training=self.training,
            momentum=self.momentum,
            epsilon=self.epsilon,
            data_format=self.data_format,
            use_global_stats=self.use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}, epsilon={self.epsilon}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid-era constructor (reference nn/layer/norm.py BatchNorm):
    num_channels/param_attr/act/data_layout names, plus accepted-but-
    absorbed knobs (is_test follows train()/eval(); in_place and the
    moving-stat names are storage details PJRT owns)."""

    def __init__(self, num_channels=None, act=None, is_test=False,
                 momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=None, trainable_statistics=False,
                 num_features=None, weight_attr=None, data_format=None,
                 name=None):
        features = num_features if num_features is not None else num_channels
        if features is None:
            raise ValueError("BatchNorm needs num_channels (or num_features)")
        super().__init__(
            features, momentum, epsilon,
            weight_attr if weight_attr is not None else param_attr,
            bias_attr, data_format if data_format is not None else data_layout,
            use_global_stats, name)
        self._act = act
        if is_test:
            self.eval()

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from . import functional as F

            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, "NCW" if data_format == "NCL" else data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.  In jit/pjit training the mesh handles stat sync
    (psum over the dp axis); eager single-process falls back to local stats.
    Reference: python/paddle/nn/layer/norm.py SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(
                layer.num_features, layer.momentum, layer.epsilon, data_format=layer.data_format
            )
            new.set_state_dict(layer.state_dict())
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter(self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={list(self.normalized_shape)}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """Root-mean-square norm (the LLM workhorse; fused Pallas path in
    paddle_tpu.incubate.nn.functional.fused_rms_norm; reference fused op:
    python/paddle/incubate/nn/functional/fused_rms_norm.py)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr, default_initializer=I.Constant(1.0)
        )

    def forward(self, x):
        from ..incubate.nn import functional as IF

        return IF.fused_rms_norm(x, self.weight, epsilon=self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter((num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight, self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0)
            )
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self.epsilon, data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 eps=None, n_power_iterations=None, dtype="float32"):
        super().__init__()
        self.dim = dim
        # torch-style aliases the reference also accepts
        self.power_iters = (n_power_iterations if n_power_iterations
                            is not None else power_iters)
        self.epsilon = eps if eps is not None else epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter((h,), default_initializer=I.Normal(0.0, 1.0))
        self.weight_v = self.create_parameter((w,), default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ..ops import manipulation as M

        w = M.moveaxis(weight, self.dim, 0)
        mat = M.reshape(w, (w.shape[0], -1))
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = F.normalize(mat.T @ u, axis=0, epsilon=self.epsilon)
            u = F.normalize(mat @ v, axis=0, epsilon=self.epsilon)
        sigma = (u @ mat @ v)
        return weight / sigma
