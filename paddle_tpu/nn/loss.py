"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""

from __future__ import annotations

from . import functional as F
from .layer_base import Layer

__all__ = [
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "SmoothL1Loss", "KLDivLoss", "MarginRankingLoss",
    "HingeEmbeddingLoss", "CosineEmbeddingLoss", "SoftMarginLoss",
    "MultiMarginLoss", "MultiLabelSoftMarginLoss", "PoissonNLLLoss",
    "GaussianNLLLoss", "TripletMarginLoss", "TripletMarginWithDistanceLoss",
    "CTCLoss", "RNNTLoss",
]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, weight=self.weight, ignore_index=self.ignore_index,
            reduction=self.reduction, soft_label=self.soft_label, axis=self.axis,
            use_softmax=self.use_softmax, label_smoothing=self.label_smoothing,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label,
                                       margin=self.margin,
                                       reduction=self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean", name=None):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self.p, margin=self.margin,
                                   weight=self.weight, reduction=self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, weight=self.weight,
                                              reduction=self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, log_input=self.log_input,
                                  full=self.full, epsilon=self.epsilon,
                                  reduction=self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, full=self.full,
                                   epsilon=self.epsilon,
                                   reduction=self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon = margin, p, epsilon
        self.swap, self.reduction = swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative,
                                     margin=self.margin, p=self.p,
                                     epsilon=self.epsilon, swap=self.swap,
                                     reduction=self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    """layer/loss.py TripletMarginWithDistanceLoss over
    F.triplet_margin_with_distance_loss."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative,
            distance_function=self.distance_function, margin=self.margin,
            swap=self.swap, reduction=self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean", name=None):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class HSigmoidLoss(Layer):
    """loss.py:457 hierarchical sigmoid loss layer over F.hsigmoid_loss.

    Owns weight [num_classes-1, feature_size] and bias [num_classes-1, 1]
    exactly like the reference; ``is_custom`` switches to caller-supplied
    path_table/path_code trees."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must not be less than 2 "
                             "with default tree")
        self.feature_size = feature_size
        self.num_classes = num_classes
        self.is_custom = is_custom
        self.is_sparse = is_sparse
        from . import initializer as I

        std = 1.0 / (num_classes ** 0.5)
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias = (None if bias_attr is False else self.create_parameter(
            (num_classes - 1, 1), attr=bias_attr, is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        if self.is_custom and (path_table is None or path_code is None):
            raise ValueError("path_table and path_code are required "
                             "when is_custom is True")
        return F.hsigmoid_loss(
            input, label, self.num_classes, self.weight, self.bias,
            path_table=path_table if self.is_custom else None,
            path_code=path_code if self.is_custom else None,
            is_sparse=self.is_sparse)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """loss.py:2393 adaptive softmax layer (Grave et al. 2017).

    head: [in_features, shortlist + n_clusters]; cluster i projects through
    [in_features, in_features/div_value^(i+1)] @ [hsz, cutoff-span] low-rank
    pairs.  forward returns (per-sample logprob, mean loss)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if (cutoffs != sorted(cutoffs) or min(cutoffs) <= 0
                or max(cutoffs) > n_classes - 1
                or len(set(cutoffs)) != len(cutoffs)
                or any(int(c) != c for c in cutoffs)):
            raise ValueError(
                "cutoffs should be a sequence of unique, positive integers "
                "sorted in an increasing order, where each value is between "
                "1 and n_classes-1")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = [*[int(c) for c in cutoffs], n_classes]
        self.div_value = div_value
        self.shortlist_size = self.cutoffs[0]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.shortlist_size + self.n_clusters
        self.head_weight = self.create_parameter(
            (in_features, self.head_size), attr=weight_attr)
        self.head_bias = (self.create_parameter(
            (self.head_size,), attr=bias_attr, is_bias=True)
            if head_bias else None)
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = int(in_features // (div_value ** (i + 1)))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w0 = self.create_parameter((in_features, hsz), attr=weight_attr)
            w1 = self.create_parameter((hsz, osz), attr=weight_attr)
            self.add_parameter(f"tail_w{i}_0", w0)
            self.add_parameter(f"tail_w{i}_1", w1)
            self.tail_weights.append([w0, w1])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probability table."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import apply_op

        n_clusters = self.n_clusters
        cutoffs = self.cutoffs
        shortlist = self.shortlist_size
        tail_flat = [w for pair in self.tail_weights for w in pair]
        inputs = ([input, self.head_weight]
                  + ([self.head_bias] if self.head_bias is not None else [])
                  + tail_flat)

        def fn(xv, hw, *rest):
            rest = list(rest)
            hb = rest.pop(0) if self.head_bias is not None else None
            head = xv @ hw + (hb if hb is not None else 0.0)
            head_lp = jax.nn.log_softmax(head, axis=1)
            pieces = [head_lp[:, :shortlist]]
            for i in range(n_clusters):
                h = (xv @ rest[2 * i]) @ rest[2 * i + 1]
                clp = jax.nn.log_softmax(h, axis=1)
                pieces.append(clp + head_lp[:, shortlist + i][:, None])
            return jnp.concatenate(pieces, axis=1)

        return apply_op("adaptive_log_prob", fn, inputs)

    def predict(self, input):
        from ..ops.manipulation import argmax

        return argmax(self.log_prob(input), axis=-1)


__all__ += ["HSigmoidLoss", "AdaptiveLogSoftmaxWithLoss"]
