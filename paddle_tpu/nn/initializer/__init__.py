"""Weight initializers (reference: python/paddle/nn/initializer/)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import rng
from ...core.tensor import Tensor, _unwrap

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Orthogonal",
    "Bilinear",
    "Dirac",
    "set_global_initializer",
    "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def _fan(shape):
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) > 2:
        # conv weight [out, in, *k] (paddle layout)
        receptive = int(np.prod(shape[2:]))
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    else:
        fan_in = fan_out = int(np.prod(shape)) if shape else 1
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError

    def init(self, param) -> None:
        param._value = jnp.asarray(self(param.shape, param.dtype))


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (
            jax.random.normal(rng.next_key(), shape, jnp.float32) * self.std + self.mean
        ).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        return (
            jax.random.truncated_normal(rng.next_key(), self.a, self.b, shape, jnp.float32)
            * self.std
            + self.mean
        ).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(
            rng.next_key(), shape, jnp.float32, self.low, self.high
        ).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(rng.next_key(), shape, jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(
            rng.next_key(), shape, jnp.float32, -limit, limit
        ).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(rng.next_key(), shape, jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(
            rng.next_key(), shape, jnp.float32, -limit, limit
        ).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.asarray(_unwrap(self.value), dtype).reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        r, c = shape[0], int(np.prod(shape[1:]))
        a = jax.random.normal(rng.next_key(), (max(r, c), min(r, c)), jnp.float32)
        q, _ = jnp.linalg.qr(a)
        q = q.T if r < c else q
        return (self.gain * q[:r, :c]).reshape(shape).astype(dtype)


class Bilinear(Initializer):
    """Bilinear-upsampling kernel for transposed convs (reference:
    python/paddle/nn/initializer/bilinear.py:110 — weight[...,y,x] =
    (1-|x/f-c|)(1-|y/f-c|) with f=ceil(K/2), c=(2f-1-f%2)/(2f), identical
    over the channel dims)."""

    def __call__(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("the length of shape must be 4.")
        if shape[2] != shape[3]:
            raise ValueError("shape[2] must be equal to shape[3].")
        size = shape[3]
        f = math.ceil(size / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        # the reference computes y with TRUE division — (i/size)%size keeps a
        # fractional x/size term — so the filter is not exactly separable;
        # replicate the flat-index formula verbatim for numerical parity
        i = np.arange(int(np.prod(shape)), dtype=np.float64)
        x = i % size
        y = (i / size) % size
        w = ((1 - np.abs(x / f - c)) * (1 - np.abs(y / f - c))).reshape(shape)
        return jnp.asarray(w.astype(np.float32), dtype)


class Dirac(Initializer):
    """Identity-preserving conv init (reference:
    python/paddle/nn/initializer/dirac.py:179 — per group i, channel j,
    weight[j+i*out_per_group, j, center...] = 1, everything else 0)."""

    def __init__(self, groups=1, name=None):
        if not (isinstance(groups, int) and groups > 0):
            raise AssertionError(" 'groups' must be a positive integer. ")
        self._groups = groups

    def __call__(self, shape, dtype):
        if not 3 <= len(shape) <= 5:
            raise ValueError("Only tensors with 3/4/5 dimensions are supported.")
        if shape[0] % self._groups != 0:
            raise AssertionError("Tensor 0-dimension must be divisible by groups")
        w = np.zeros(shape, dtype=np.float32)
        num_per_group = shape[0] // self._groups
        min_shape = min(num_per_group, shape[1])
        center = tuple(s // 2 for s in shape[2:])
        for i in range(self._groups):
            for j in range(min_shape):
                w[(j + i * num_per_group, j) + center] = 1.0
        return jnp.asarray(w, dtype)


# global default initializers consulted by Layer.create_parameter when a
# param/bias attr does not carry its own (reference:
# python/paddle/base/initializer.py:46 — attr-level initializers win)
_global_weight_init: Initializer | None = None
_global_bias_init: Initializer | None = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


# lowercase aliases matching paddle.nn.initializer usage in configs
constant = Constant
normal = Normal
uniform = Uniform
