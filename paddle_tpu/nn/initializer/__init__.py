"""Weight initializers (reference: python/paddle/nn/initializer/)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import rng
from ...core.tensor import Tensor, _unwrap

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Orthogonal",
    "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def _fan(shape):
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) > 2:
        # conv weight [out, in, *k] (paddle layout)
        receptive = int(np.prod(shape[2:]))
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    else:
        fan_in = fan_out = int(np.prod(shape)) if shape else 1
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError

    def init(self, param) -> None:
        param._value = jnp.asarray(self(param.shape, param.dtype))


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (
            jax.random.normal(rng.next_key(), shape, jnp.float32) * self.std + self.mean
        ).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        return (
            jax.random.truncated_normal(rng.next_key(), self.a, self.b, shape, jnp.float32)
            * self.std
            + self.mean
        ).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(
            rng.next_key(), shape, jnp.float32, self.low, self.high
        ).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(rng.next_key(), shape, jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(
            rng.next_key(), shape, jnp.float32, -limit, limit
        ).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(rng.next_key(), shape, jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(
            rng.next_key(), shape, jnp.float32, -limit, limit
        ).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.asarray(_unwrap(self.value), dtype).reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        r, c = shape[0], int(np.prod(shape[1:]))
        a = jax.random.normal(rng.next_key(), (max(r, c), min(r, c)), jnp.float32)
        q, _ = jnp.linalg.qr(a)
        q = q.T if r < c else q
        return (self.gain * q[:r, :c]).reshape(shape).astype(dtype)


# lowercase aliases matching paddle.nn.initializer usage in configs
constant = Constant
normal = Normal
uniform = Uniform
