"""Seq2seq decoding API (reference: python/paddle/nn/decode.py —
Decoder:50, BeamSearchDecoder:161, dynamic_decode:1279).

TPU-native design: beams are merged into the batch dimension
([batch*beam, ...]) so every step is one batched cell call; the decode loop
runs eagerly (each step is a jitted dispatch) mirroring the reference's
imperative path, and the final back-trace is the in-graph
``F.gather_tree`` scan."""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, _unwrap
from . import functional as F

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]

_INF = 1e9


class Decoder:
    """decode.py:50 — interface: initialize / step / finalize."""

    tracks_own_finished = False

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError


BeamSearchDecoderOutput = collections.namedtuple(
    "BeamSearchDecoderOutput", ["scores", "predicted_ids", "parent_ids"])
BeamSearchState = collections.namedtuple(
    "BeamSearchState", ["cell_states", "log_probs", "finished", "lengths"])


def _map_state(fn, state):
    if isinstance(state, (tuple, list)):
        return type(state)(_map_state(fn, s) for s in state)
    return fn(_unwrap(state))


def _zip_state(fn, a, b):
    if isinstance(a, (tuple, list)):
        return type(a)(_zip_state(fn, x, y) for x, y in zip(a, b))
    return fn(_unwrap(a), _unwrap(b))


class BeamSearchDecoder(Decoder):
    """decode.py:161 — beam search over an RNNCellBase-like cell."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] with each row repeated."""
        v = _unwrap(x)
        v = jnp.repeat(v, beam_size, axis=0)
        return Tensor(v) if isinstance(x, Tensor) else v

    def _merge(self, v):
        # [batch, beam, ...] -> [batch*beam, ...]
        return v.reshape((-1,) + v.shape[2:])

    def _split(self, v):
        return v.reshape((-1, self.beam_size) + v.shape[1:])

    def initialize(self, initial_cell_states):
        cell_states = _map_state(
            lambda v: jnp.repeat(v, self.beam_size, axis=0),
            initial_cell_states)
        some = cell_states
        while isinstance(some, (tuple, list)):
            some = some[0]
        batch = some.shape[0] // self.beam_size
        log_probs = jnp.tile(
            jnp.array([0.0] + [-_INF] * (self.beam_size - 1), jnp.float32),
            (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int32)
        init_ids = jnp.full((batch, self.beam_size), self.start_token,
                            jnp.int32)
        inputs = self._embed(init_ids)
        return inputs, BeamSearchState(cell_states, log_probs, finished,
                                       lengths), finished

    def _embed(self, ids):
        # ids: [batch, beam] -> merged [batch*beam(, emb)] so the cell always
        # sees the same leading dim as its (merged) states
        if self.embedding_fn is None:
            return self._merge(ids)
        out = self.embedding_fn(Tensor(self._merge(ids)))
        return _unwrap(out)

    def step(self, time, inputs, states, **kwargs):
        beam = self.beam_size
        cell_inputs = inputs if isinstance(inputs, Tensor) else Tensor(inputs)
        cell_state_t = _map_state(Tensor, states.cell_states)
        cell_out, next_cell_states = self.cell(cell_inputs, cell_state_t,
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = _unwrap(cell_out)                      # [batch*beam, vocab]
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        step_lp = self._split(step_lp)                  # [batch, beam, vocab]
        # finished beams may only emit end_token, at no extra cost
        fin = states.finished[:, :, None]
        noend = jnp.full((vocab,), -_INF, jnp.float32).at[self.end_token].set(0.0)
        step_lp = jnp.where(fin, noend[None, None, :], step_lp)
        total = states.log_probs[:, :, None] + step_lp  # [batch, beam, vocab]
        flat = total.reshape(total.shape[0], -1)
        scores, flat_idx = jax.lax.top_k(flat, beam)    # [batch, beam]
        parents = (flat_idx // vocab).astype(jnp.int32)
        tokens = (flat_idx % vocab).astype(jnp.int32)
        batch_idx = jnp.arange(flat.shape[0])[:, None]
        next_finished = states.finished[batch_idx, parents] | \
            (tokens == self.end_token)
        next_lengths = states.lengths[batch_idx, parents] + \
            (~states.finished[batch_idx, parents]).astype(jnp.int32)
        gather = lambda v: self._merge(
            self._split(v)[batch_idx, parents])
        next_cells = _map_state(gather, next_cell_states)
        next_inputs = self._embed(tokens)
        outputs = BeamSearchDecoderOutput(scores, tokens, parents)
        next_states = BeamSearchState(next_cells, scores, next_finished,
                                      next_lengths)
        return outputs, next_states, next_inputs, next_finished

    def finalize(self, outputs, final_states, sequence_lengths):
        predicted = F.gather_tree(Tensor(outputs.predicted_ids),
                                  Tensor(outputs.parent_ids))
        return BeamSearchDecoderOutput(
            Tensor(outputs.scores), predicted, Tensor(outputs.parent_ids))


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """decode.py:1279 — step the decoder until every beam finishes or
    ``max_step_num`` is hit, then stack the per-step outputs over time and
    hand them to ``decoder.finalize``."""
    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    time = 0
    while True:
        outputs, next_states, inputs, finished = decoder.step(
            time, inputs, states, **kwargs)
        step_outputs.append(outputs)
        if impute_finished and not decoder.tracks_own_finished:
            # rows already finished BEFORE this step keep their old cell
            # state, so final_states is exact at each row's own end step
            prev_fin = states.finished.reshape(-1)

            def _carry(old, new):
                m = prev_fin.reshape((-1,) + (1,) * (new.ndim - 1))
                return jnp.where(m, old, new)

            next_states = next_states._replace(cell_states=_zip_state(
                _carry, states.cell_states, next_states.cell_states))
        states = next_states
        time += 1
        if bool(jnp.all(finished)):
            break
        if max_step_num is not None and time > int(max_step_num):
            break
    stacked = type(step_outputs[0])(*(
        jnp.stack([getattr(o, f) for o in step_outputs])
        for f in step_outputs[0]._fields))
    lengths = states.lengths
    final = decoder.finalize(stacked, states, lengths)
    if not output_time_major:
        final = type(final)(*(
            Tensor(jnp.swapaxes(_unwrap(f), 0, 1)) for f in final))
    if return_length:
        return final, Tensor(lengths)
    return final
