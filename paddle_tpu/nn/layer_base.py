"""Layer base class + ParamAttr.

Reference: ``paddle.nn.Layer`` (python/paddle/base/dygraph/layers.py) — named
parameter/buffer/sublayer trees, state_dict round-trip, train/eval modes, hooks.
Parameters are eager Tensors; the functional bridge for jit/pjit training is in
paddle_tpu.jit (parameters ↔ pytree).
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Parameter, Tensor, _unwrap

__all__ = ["Layer", "ParamAttr"]


class ParamAttr:
    """Parameter attribute bundle (reference: python/paddle/base/param_attr.py)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None or attr is True:
            return ParamAttr()
        if attr is False:
            return None
        if isinstance(attr, ParamAttr):
            return attr
        # a bare initializer
        return ParamAttr(initializer=attr)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        object.__setattr__(self, "_forward_pre_hooks", collections.OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", collections.OrderedDict())
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name = name_scope or self.__class__.__name__

    # ------------- attribute routing -------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__() before assigning parameters")
            params[name] = value
            subs.pop(name, None) if subs else None
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            subs[name] = value
            params.pop(name, None) if params else None
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                del params[name]
                object.__setattr__(self, name, None)
            else:
                params[name] = value
        elif bufs is not None and name in bufs:
            bufs[name] = value if (value is None or isinstance(value, Tensor)) else Tensor(jnp.asarray(value))
        elif subs is not None and name in subs and value is None:
            del subs[name]
            object.__setattr__(self, name, None)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # ------------- construction helpers -------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        from . import initializer as I

        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        dtype = dtypes.convert_dtype(dtype) if dtype is not None else self._dtype
        # precedence (reference: python/paddle/base/initializer.py:46): an
        # initializer set via ParamAttr wins; the global initializer beats
        # the layer's built-in default
        init = attr.initializer
        if init is None:
            init = I._global_bias_init if is_bias else I._global_weight_init
        if init is None:
            init = default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        value = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------- traversal -------------
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for name, layer_prefix in self._traverse(prefix, include_sublayers):
            for pname, p in name._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (layer_prefix + pname, p)

    def _traverse(self, prefix="", include_sublayers=True):
        yield self, prefix
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                yield from sub._traverse(prefix + sname + ".", True)

    def parameters(self, include_sublayers=True) -> list:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = prefix + ("." if prefix else "") + name
            yield p, sub
            yield from sub.named_sublayers(prefix=p)

    def sublayers(self, include_self=False) -> list:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter([l for l in self._sub_layers.values() if l is not None])

    def named_children(self):
        return iter([(n, l) for n, l in self._sub_layers.items() if l is not None])

    def named_buffers(self, prefix="", include_sublayers=True):
        for layer, layer_prefix in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None:
                    yield (layer_prefix + bname, b)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ------------- modes -------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ------------- state dict -------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for layer, prefix in self._traverse("", include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and bname not in layer._non_persistable_buffer_names:
                    dest[prefix + bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                val = _unwrap(v) if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                tgt._value = jnp.asarray(val, tgt.dtype).reshape(tgt.shape)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------- dtype / device movement -------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtypes.convert_dtype(dtype)
            for t in list(self.parameters()) + list(self.buffers()):
                if dtypes.is_floating(t.dtype):
                    t._value = t._value.astype(dt)
            for l in self.sublayers(include_self=True):
                l._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------- hooks -------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks, len(self._forward_pre_hooks))
        self._forward_pre_hooks[handle.idx] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks, len(self._forward_post_hooks))
        self._forward_post_hooks[handle.idx] = hook
        return handle

    # ------------- call -------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [extra] if extra else []
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_repr = repr(sub).split("\n")
            lines.append(f"({name}): " + sub_repr[0])
            lines.extend("  " + l for l in sub_repr[1:])
        main = self.__class__.__name__
        if not lines:
            return f"{main}({extra})"
        return main + "(\n  " + "\n  ".join(lines) + "\n)"

    def full_name(self):
        return self._name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class _HookHandle:
    def __init__(self, store, idx):
        self._store = store
        self.idx = idx

    def remove(self):
        self._store.pop(self.idx, None)
