"""Weight-only quantization ops (`paddle.nn.quant` parity).

Reference surface: python/paddle/nn/quant/quantized_linear.py —
``weight_quantize`` (:64), ``weight_dequantize`` (:131),
``weight_only_linear`` (:191), ``llm_int8_linear`` (:285), backed there by
CUDA cutlass kernels (phi/ops/yaml/ops.yaml:5320 ``weight_only_linear``).

TPU-native design: the quantized weight is stored int8 (or NATIVE jnp.int4 —
XLA packs int4 two-per-byte in HBM, so the 4x footprint win is real, no
manual bit-packing needed), and the linear runs as a dequant-into-matmul
that XLA fuses: the weight is read from HBM at 1/2 or 1/4 the bytes of
bf16, which is exactly what matters in the bandwidth-bound decode regime.
No CUDA arch dispatch: ``arch`` is accepted and ignored.

Storage convention follows the reference: ``weight_quantize(x[K, N])``
returns the TRANSPOSED quantized weight ``[N, K]`` plus per-channel (or
grouped) float32 scales.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import apply_op

__all__ = [
    "weight_quantize",
    "weight_dequantize",
    "weight_only_linear",
    "llm_int8_linear",
]

_BOUNDS = {"weight_only_int8": 127.0, "llm.int8": 127.0, "weight_only_int4": 7.0}


def _check_group(group_size):
    assert group_size in (-1, 64, 128), (
        f"group_size must be -1, 64 or 128, got {group_size}")


def _quantize_2d(w, algo: str, group_size: int = -1):
    """Raw-array core of :func:`weight_quantize`: [K, N] -> (q [N, K],
    scale) — shared with the inference engines' weight-only mode."""
    assert w.ndim == 2, f"weight must be rank-2, got {w.shape}"
    bound = _BOUNDS[algo]
    K, N = w.shape
    w32 = w.astype(jnp.float32)
    if group_size == -1:
        absmax = jnp.max(jnp.abs(w32), axis=0)          # [N]
        scale = absmax / bound
        q = jnp.round(w32 / jnp.maximum(scale, 1e-10)[None, :])
    else:
        assert K % group_size == 0, (K, group_size)
        g = w32.reshape(K // group_size, group_size, N)
        absmax = jnp.max(jnp.abs(g), axis=1)            # [K/gs, N]
        scale = absmax / bound
        q = jnp.round(g / jnp.maximum(scale, 1e-10)[:, None, :]).reshape(K, N)
    q = jnp.clip(q, -bound, bound)
    store = jnp.int4 if algo == "weight_only_int4" else jnp.int8
    return q.T.astype(store), scale.astype(jnp.float32)


def weight_quantize(x, algo: str = "weight_only_int8", arch=None,
                    group_size: int = -1):
    """Quantize a [K, N] weight; returns (out, scale) with out [N, K]
    (transposed, the reference's layout) and float32 scales: [N] per-channel
    (group_size == -1) or [K // group_size, N] grouped.

    ``weight_only_int4`` stores jnp.int4 (packed by XLA); int8 otherwise.
    ``arch`` (a CUDA SM number in the reference) is ignored on TPU."""
    del arch
    _check_group(group_size)
    assert algo in _BOUNDS, f"unknown algo {algo!r}"
    return apply_op("weight_quantize",
                    lambda w: _quantize_2d(w, algo, group_size), [x])


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype="float16", group_size: int = -1):
    """Inverse of :func:`weight_quantize`: [N, K] + scales -> [K, N]."""
    _check_group(group_size)

    def fn(q, s):
        return _dequant_2d(q, s, jnp.float32, group_size).astype(jnp.dtype(out_dtype))

    return apply_op("weight_dequantize", fn, [x, scale])


def _dequant_2d(q, s, dt, group_size: int = -1):
    """Raw-array dequant of the [N, K] transposed storage -> dense [K, N]
    in dtype ``dt`` — the single home of the layout convention (the
    engines' weight-only matmuls use this too; XLA fuses the multiply into
    the consuming matmul's HBM read)."""
    w = q.T.astype(dt)  # [K, N]
    if group_size == -1:
        w = w * s[None, :].astype(dt)
    else:
        K, N = w.shape
        w = (w.reshape(K // group_size, group_size, N)
             * s[:, None, :].astype(dt)).reshape(K, N)
    return w


def _dequant_matmul(xv, q, s, group_size, bias=None):
    """x [..., K] @ dequant(q [N, K], s) -> [..., N]."""
    out = xv @ _dequant_2d(q, s, xv.dtype, group_size)
    if bias is not None:
        out = out + bias
    return out


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None,
                       group_size: int = -1):
    """x [..., K] times a weight quantized by :func:`weight_quantize`
    (stored [N, K], int8 or int4) with dequantization fused into the matmul.
    Matches the reference op semantics (ops.yaml:5320)."""
    del arch
    _check_group(group_size)
    assert weight_dtype in ("int8", "int4"), weight_dtype

    def fn(xv, q, s, *rest):
        return _dequant_matmul(xv, q, s, group_size,
                               rest[0] if rest else None)

    inputs = [x, weight, weight_scale] + ([bias] if bias is not None else [])
    return apply_op("weight_only_linear", fn, inputs)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0):
    """LLM.int8 matmul (reference quantized_linear.py:285): activation
    channels whose absmax exceeds ``threshold`` (the outliers) run in the
    activation dtype against the dequantized weight columns; the rest runs
    as a dynamically-quantized int8 x int8 dot (int32 accumulation on the
    MXU) with per-row activation scales.  Static shapes: the outlier set is
    a mask, not a gather, so one compiled program serves every batch."""

    def fn(xv, q, s, *rest):
        dt = xv.dtype
        K = xv.shape[-1]
        # outlier channels: feature dims with any |x| > threshold
        col_max = jnp.max(jnp.abs(xv.astype(jnp.float32)),
                          axis=tuple(range(xv.ndim - 1)))      # [K]
        outlier = col_max > threshold
        x_out = jnp.where(outlier, xv, 0)  # [K] broadcasts from the right
        x_int_part = xv - x_out
        # dynamic per-row int8 quantization of the inlier part
        row_max = jnp.max(jnp.abs(x_int_part.astype(jnp.float32)),
                          axis=-1, keepdims=True)
        sx = jnp.maximum(row_max / 127.0, 1e-10)
        xq = jnp.round(x_int_part.astype(jnp.float32) / sx).astype(jnp.int8)
        # int8 x int8 -> int32 dot; dequant epilogue applies sx (row) and
        # the weight's per-channel scale
        acc = jax.lax.dot_general(
            xq, q.T, (((xq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y_int = acc.astype(jnp.float32) * sx * s[None, :]
        # outlier columns in full precision
        w_out = q.T.astype(jnp.float32) * s[None, :]
        w_out = jnp.where(outlier[:, None], w_out, 0)
        y = y_int + x_out.astype(jnp.float32) @ w_out
        out = y.astype(dt)
        if rest:
            out = out + rest[0]
        return out

    inputs = [x, weight, weight_scale] + ([bias] if bias is not None else [])
    return apply_op("llm_int8_linear", fn, inputs)
