"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native design: the whole sequence loop is one ``jax.lax.scan`` per layer and
direction — compiles to a single fused XLA while-loop instead of a Python loop of
kernel launches (the reference relies on cuDNN RNN kernels here)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from . import initializer as I
from .layer_base import Layer

__all__ = ["SimpleRNN", "LSTM", "GRU", "LSTMCell", "GRUCell", "SimpleRNNCell", "RNN"]


def _rnn_params(layer, input_size, hidden_size, gates, suffix,
                weight_attr=None, bias_attr=None, weight_ih_attr=None,
                weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
    # per-part attrs (the reference's rnn.py granularity) win over the
    # coarse weight_attr/bias_attr pair
    std = 1.0 / math.sqrt(hidden_size)
    wi = layer.create_parameter(
        (gates * hidden_size, input_size),
        attr=weight_ih_attr if weight_ih_attr is not None else weight_attr,
        default_initializer=I.Uniform(-std, std)
    )
    wh = layer.create_parameter(
        (gates * hidden_size, hidden_size),
        attr=weight_hh_attr if weight_hh_attr is not None else weight_attr,
        default_initializer=I.Uniform(-std, std)
    )
    bi = layer.create_parameter(
        (gates * hidden_size,),
        attr=bias_ih_attr if bias_ih_attr is not None else bias_attr,
        is_bias=True, default_initializer=I.Uniform(-std, std)
    )
    bh = layer.create_parameter(
        (gates * hidden_size,),
        attr=bias_hh_attr if bias_hh_attr is not None else bias_attr,
        is_bias=True, default_initializer=I.Uniform(-std, std)
    )
    layer.add_parameter(f"weight_ih_{suffix}", wi)
    layer.add_parameter(f"weight_hh_{suffix}", wh)
    layer.add_parameter(f"bias_ih_{suffix}", bi)
    layer.add_parameter(f"bias_hh_{suffix}", bh)
    return wi, wh, bi, bh


def _lstm_step(h, c, x_t, wi, wh, bi, bh):
    z = x_t @ wi.T + h @ wh.T + bi + bh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


def _gru_step(h, x_t, wi, wh, bi, bh):
    xz = x_t @ wi.T + bi
    hz = h @ wh.T + bh
    xr, xu, xn = jnp.split(xz, 3, axis=-1)
    hr, hu, hn = jnp.split(hz, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    u = jax.nn.sigmoid(xu + hu)
    n = jnp.tanh(xn + r * hn)
    return (1 - u) * n + u * h


def _simple_step(h, x_t, wi, wh, bi, bh, act):
    z = x_t @ wi.T + h @ wh.T + bi + bh
    return jnp.tanh(z) if act == "tanh" else jax.nn.relu(z)


class _RNNBase(Layer):
    MODE = "LSTM"
    GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}

    def __init__(
        self,
        input_size,
        hidden_size,
        num_layers=1,
        direction="forward",
        time_major=False,
        dropout=0.0,
        activation="tanh",
        weight_attr=None,
        bias_attr=None,
        weight_ih_attr=None,
        weight_hh_attr=None,
        bias_ih_attr=None,
        bias_hh_attr=None,
        proj_size=0,
        name=None,
    ):
        super().__init__()
        if proj_size:
            raise NotImplementedError(
                "LSTM proj_size (LSTMP cell projection) is not supported; "
                "project the outputs with a Linear layer instead")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        gates = self.GATES[self.MODE if self.MODE != "RNN" else f"RNN_{activation.upper()}"]
        self._weights = []
        for layer_i in range(num_layers):
            for d in range(self.bidirect):
                in_sz = input_size if layer_i == 0 else hidden_size * self.bidirect
                suffix = f"l{layer_i}" + ("_reverse" if d == 1 else "")
                self._weights.append(
                    _rnn_params(self, in_sz, hidden_size, gates, suffix,
                                weight_attr, bias_attr, weight_ih_attr,
                                weight_hh_attr, bias_ih_attr, bias_hh_attr)
                )

    def _scan_layer(self, seq_len):
        mode = self.MODE
        act = self.activation

        def run(x, h0, c0, wi, wh, bi, bh, reverse):
            # x: [seq, batch, in]
            xs = jnp.flip(x, axis=0) if reverse else x

            if mode == "LSTM":

                def step(carry, x_t):
                    h, c = carry
                    h2, c2 = _lstm_step(h, c, x_t, wi, wh, bi, bh)
                    return (h2, c2), h2

                (hT, cT), ys = jax.lax.scan(step, (h0, c0), xs)
            elif mode == "GRU":

                def step(h, x_t):
                    h2 = _gru_step(h, x_t, wi, wh, bi, bh)
                    return h2, h2

                hT, ys = jax.lax.scan(step, h0, xs)
                cT = hT
            else:

                def step(h, x_t):
                    h2 = _simple_step(h, x_t, wi, wh, bi, bh, act)
                    return h2, h2

                hT, ys = jax.lax.scan(step, h0, xs)
                cT = hT
            if reverse:
                ys = jnp.flip(ys, axis=0)
            return ys, hT, cT

        return run

    def forward(self, inputs, initial_states=None, sequence_length=None):
        n_states = self.num_layers * self.bidirect
        is_lstm = self.MODE == "LSTM"
        weight_tensors = [t for ws in self._weights for t in ws]

        def fn(x, *flat):
            ws = [flat[i * 4 : (i + 1) * 4] for i in range(len(self._weights))]
            k = len(self._weights) * 4
            if initial_states is not None:
                if is_lstm:
                    h0_all, c0_all = flat[k], flat[k + 1]
                else:
                    h0_all = flat[k]
                    c0_all = jnp.zeros_like(h0_all)
            else:
                b = x.shape[0] if not self.time_major else x.shape[1]
                h0_all = jnp.zeros((n_states, b, self.hidden_size), x.dtype)
                c0_all = jnp.zeros_like(h0_all)

            xs = x if self.time_major else jnp.swapaxes(x, 0, 1)  # [seq, batch, in]
            run = self._scan_layer(xs.shape[0])
            hs, cs = [], []
            out = xs
            idx = 0
            for layer_i in range(self.num_layers):
                outs_dir = []
                for d in range(self.bidirect):
                    wi, wh, bi, bh = ws[idx]
                    ys, hT, cT = run(out, h0_all[idx], c0_all[idx], wi, wh, bi, bh, d == 1)
                    outs_dir.append(ys)
                    hs.append(hT)
                    cs.append(cT)
                    idx += 1
                out = outs_dir[0] if self.bidirect == 1 else jnp.concatenate(outs_dir, axis=-1)
            final_h = jnp.stack(hs)
            final_c = jnp.stack(cs)
            out = out if self.time_major else jnp.swapaxes(out, 0, 1)
            if is_lstm:
                return out, final_h, final_c
            return out, final_h

        inputs_list = [inputs] + weight_tensors
        if initial_states is not None:
            if is_lstm:
                inputs_list += [initial_states[0], initial_states[1]]
            else:
                inputs_list += [initial_states]
        res = apply_op(self.MODE.lower(), fn, inputs_list)
        if is_lstm:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class SimpleRNN(_RNNBase):
    MODE = "RNN"

    @property
    def GATES(self):
        return {"RNN_TANH": 1, "RNN_RELU": 1}


SimpleRNN.GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}


class RNNCellBase(Layer):
    """rnn.py:591 RNNCellBase — base for cells usable with RNN/BiRNN and the
    decoding API; provides zero-filled initial states shaped per batch.
    ``state_shape`` is a (possibly nested) tuple of per-state trailing shapes;
    cells with tuple states (LSTM) override it and receive matching nested
    initial states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ..core.dtype import convert_dtype

        batch = batch_ref.shape[batch_dim_idx]
        spec = shape if shape is not None else self.state_shape
        jdtype = jnp.float32 if dtype is None else convert_dtype(dtype)

        def build(s):
            if isinstance(s, (tuple, list)) and s and isinstance(s[0], (tuple, list)):
                return tuple(build(sub) for sub in s)
            return Tensor(jnp.full((batch,) + tuple(int(d) for d in s),
                                   init_value, jdtype))

        return build(spec)

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_attr=None, bias_attr=None,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, proj_size=0, name=None):
        if proj_size:
            raise NotImplementedError("LSTMCell proj_size is not supported")
        super().__init__()
        self.hidden_size = hidden_size
        self.wi, self.wh, self.bi, self.bh = None, None, None, None
        ws = _rnn_params(self, input_size, hidden_size, 4, "cell", weight_attr, bias_attr, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)
        self._ws = ws

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        wi, wh, bi, bh = (
            self._parameters["weight_ih_cell"],
            self._parameters["weight_hh_cell"],
            self._parameters["bias_ih_cell"],
            self._parameters["bias_hh_cell"],
        )
        if states is None:
            b = inputs.shape[0]
            z = Tensor(jnp.zeros((b, self.hidden_size), jnp.float32))
            states = (z, z)

        def fn(x, h, c, wi_, wh_, bi_, bh_):
            return _lstm_step(h, c, x, wi_, wh_, bi_, bh_)

        h2, c2 = apply_op("lstm_cell", fn, [inputs, states[0], states[1], wi, wh, bi, bh])
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_attr=None, bias_attr=None,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _rnn_params(self, input_size, hidden_size, 3, "cell", weight_attr, bias_attr, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        wi, wh, bi, bh = (
            self._parameters["weight_ih_cell"],
            self._parameters["weight_hh_cell"],
            self._parameters["bias_ih_cell"],
            self._parameters["bias_hh_cell"],
        )
        if states is None:
            states = Tensor(jnp.zeros((inputs.shape[0], self.hidden_size), jnp.float32))

        def fn(x, h, wi_, wh_, bi_, bh_):
            return _gru_step(h, x, wi_, wh_, bi_, bh_)

        h2 = apply_op("gru_cell", fn, [inputs, states, wi, wh, bi, bh])
        return h2, h2


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_attr=None, bias_attr=None, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        _rnn_params(self, input_size, hidden_size, 1, "cell", weight_attr, bias_attr, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        wi, wh, bi, bh = (
            self._parameters["weight_ih_cell"],
            self._parameters["weight_hh_cell"],
            self._parameters["bias_ih_cell"],
            self._parameters["bias_hh_cell"],
        )
        if states is None:
            states = Tensor(jnp.zeros((inputs.shape[0], self.hidden_size), jnp.float32))

        def fn(x, h, wi_, wh_, bi_, bh_):
            return _simple_step(h, x, wi_, wh_, bi_, bh_, self.activation)

        h2 = apply_op("rnn_cell", fn, [inputs, states, wi, wh, bi, bh])
        return h2, h2


class RNN(Layer):
    """Wrap a cell into a sequence runner (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        seq_axis = 0 if self.time_major else 1
        length = inputs.shape[seq_axis]
        idxs = range(length - 1, -1, -1) if self.is_reverse else range(length)
        outs = []
        states = initial_states
        from ..ops import manipulation as M

        for i in idxs:
            x_t = M.squeeze(M.slice(inputs, [seq_axis], [i], [i + 1]), axis=seq_axis)
            y, states = self.cell(x_t, states)
            outs.append(y)
        if self.is_reverse:
            outs = outs[::-1]
        out = M.stack(outs, axis=seq_axis)
        return out, states


class BiRNN(Layer):
    """rnn.py BiRNN: run a forward cell and a backward cell over the sequence
    and concatenate the outputs feature-wise."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.time_major = time_major
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, fin_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, fin_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        from ..ops.manipulation import concat

        return concat([out_fw, out_bw], axis=-1), (fin_fw, fin_bw)


__all__ += ["RNNCellBase", "BiRNN"]
