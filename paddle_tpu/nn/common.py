"""Common layers (reference: python/paddle/nn/layer/common.py)."""

from __future__ import annotations

import math

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer_base import Layer, ParamAttr

__all__ = [
    "Identity",
    "PairwiseDistance",
    "ChannelShuffle",
    "Fold",
    "Unfold",
    "Linear",
    "Embedding",
    "Dropout",
    "Dropout2D",
    "Dropout3D",
    "AlphaDropout",
    "Flatten",
    "Unflatten",
    "Pad1D",
    "Pad2D",
    "Pad3D",
    "Upsample",
    "UpsamplingBilinear2D",
    "UpsamplingNearest2D",
    "PixelShuffle",
    "PixelUnshuffle",
    "CosineSimilarity",
    "Bilinear",
]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = x W + b, weight shape [in, out] (paddle layout)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=weight_attr,
            default_initializer=I.XavierUniform(),
        )
        self.bias = self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True
        )

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(
        self,
        num_embeddings,
        embedding_dim,
        padding_idx=None,
        sparse=False,
        weight_attr=None,
        max_norm=None,
        norm_type=2.0,
        scale_grad_by_freq=False,
        name=None,
    ):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.scale_grad_by_freq = scale_grad_by_freq
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0),
        )
        if padding_idx is not None:
            self.weight._value = self.weight._value.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx,
                           max_norm=self.max_norm, norm_type=self.norm_type,
                           scale_grad_by_freq=self.scale_grad_by_freq)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..ops.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ..ops.manipulation import reshape

        new_shape = list(x.shape)
        new_shape[self.axis : self.axis + 1] = list(self.shape)
        return reshape(x, new_shape)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value, data_format=self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    pass


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(
            x, self.size, self.scale_factor, self.mode, self.align_corners, self.align_mode, self.data_format
        )


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, data_format=data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr
        )
        self.bias = self.create_parameter((1, out_features), attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PairwiseDistance(Layer):
    """layer/distance.py PairwiseDistance."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class ChannelShuffle(Layer):
    """layer/vision.py ChannelShuffle."""

    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Fold(Layer):
    """layer/common.py Fold (col2im)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes, self.kernel_sizes = output_sizes, kernel_sizes
        self.strides, self.paddings, self.dilations = strides, paddings, dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class Unfold(Layer):
    """layer/common.py Unfold (im2col)."""

    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides, self.paddings, self.dilations = strides, paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class ZeroPad2D(Layer):
    """common.py ZeroPad2D over F.zeropad2d."""

    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.data_format = padding, data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


class _ZeroPadNd(Layer):
    """Shared zero-pad forward over F.pad (one padding entry point)."""

    _n = 2

    def __init__(self, padding, data_format=None, name=None):
        super().__init__()
        self.padding = ([padding] * (2 * self._n) if isinstance(padding, int)
                        else list(padding))
        self.data_format = data_format or self._fmt

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format=self.data_format)


class ZeroPad1D(_ZeroPadNd):
    """common.py ZeroPad1D: zero-pad the last axis by [left, right]."""

    _n, _fmt = 1, "NCL"


class ZeroPad3D(_ZeroPadNd):
    """common.py ZeroPad3D: zero-pad D/H/W by [l, r, t, b, f, bk]."""

    _n, _fmt = 3, "NCDHW"


class FeatureAlphaDropout(Layer):
    """common.py FeatureAlphaDropout over F.feature_alpha_dropout."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


__all__ += ["ZeroPad1D", "ZeroPad2D", "ZeroPad3D", "FeatureAlphaDropout"]
