"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

from . import functional as F
from .layer_base import Layer

__all__ = [
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
    "LPPool1D", "LPPool2D", "FractionalMaxPool2D", "FractionalMaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
]


class _Pool(Layer):
    """Positional argument orders match the reference exactly
    (layer/pooling.py): MaxPool* take (..., return_mask, ceil_mode),
    AvgPool1D (..., exclusive, ceil_mode), AvgPool2D/3D
    (..., ceil_mode, exclusive, divisor_override)."""

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, exclusive=True, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.exclusive = exclusive
        self.kw = kw


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         return_mask=return_mask)

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         return_mask=return_mask)

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         return_mask=return_mask)

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            exclusive=self.exclusive,
                            ceil_mode=self.ceil_mode)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            exclusive=self.exclusive,
                            ceil_mode=self.ceil_mode)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode,
                         exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            exclusive=self.exclusive,
                            ceil_mode=self.ceil_mode)


class _AdaptivePool(Layer):
    def __init__(self, output_size, return_mask=False, data_format=None,
                 name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, data_format=self.data_format or "NCHW")


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, data_format=self.data_format or "NCDHW")


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class LPPool2D(Layer):
    """layer/pooling.py LPPool2D over F.lp_pool2d."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.norm_type, self.kernel_size = norm_type, kernel_size
        self.stride, self.padding = stride, padding
        self.ceil_mode, self.data_format = ceil_mode, data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class FractionalMaxPool2D(Layer):
    """layer/pooling.py FractionalMaxPool2D over F.fractional_max_pool2d."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class LPPool1D(Layer):
    """layer/pooling.py LPPool1D over F.lp_pool1d."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type, self.kernel_size = norm_type, kernel_size
        self.stride, self.padding = stride, padding
        self.ceil_mode, self.data_format = ceil_mode, data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class FractionalMaxPool3D(Layer):
    """layer/pooling.py FractionalMaxPool3D over F.fractional_max_pool3d."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class _MaxUnPool(Layer):
    _fn = None
    _fmt = "NCHW"

    def __init__(self, kernel_size, stride=None, padding=0, data_format=None,
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format or self._fmt
        self.output_size = output_size

    def forward(self, x, indices):
        return type(self)._fn(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class MaxUnPool1D(_MaxUnPool):
    _fn = staticmethod(F.max_unpool1d)
    _fmt = "NCL"


class MaxUnPool2D(_MaxUnPool):
    _fn = staticmethod(F.max_unpool2d)
    _fmt = "NCHW"


class MaxUnPool3D(_MaxUnPool):
    _fn = staticmethod(F.max_unpool3d)
    _fmt = "NCDHW"
