"""Convolution layers (reference: python/paddle/nn/layer/conv.py).

Weight layout follows paddle: [out_channels, in_channels // groups, *kernel];
transposed conv: [in_channels, out_channels // groups, *kernel].  XLA's
conv_general_dilated maps both directly onto the MXU."""

from __future__ import annotations

import math

import numpy as np

from . import functional as F
from . import initializer as I
from .layer_base import Layer

__all__ = [
    "Conv1D",
    "Conv2D",
    "Conv3D",
    "Conv1DTranspose",
    "Conv2DTranspose",
    "Conv3DTranspose",
]


def _ntuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(
        self,
        in_channels,
        out_channels,
        kernel_size,
        ndim,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        padding_mode="zeros",
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
        transposed=False,
        output_padding=0,
    ):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, ndim)
        self.stride = _ntuple(stride, ndim)
        self.padding = padding
        self.dilation = _ntuple(dilation, ndim)
        self.groups = groups
        self.data_format = data_format
        self.output_padding = output_padding
        self._ndim = ndim
        self._transposed = transposed
        if transposed:
            shape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            shape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = in_channels // groups * int(np.prod(self.kernel_size))
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=I.KaimingUniform(negative_slope=math.sqrt(5), nonlinearity="leaky_relu")
        )
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound),
        )


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transposed=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.groups, self.dilation, output_size=output_size, data_format=self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, dilation=1, groups=1, weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transposed=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.groups, self.dilation, output_size=output_size, data_format=self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, dilation=1, groups=1, weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transposed=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.groups, self.dilation, output_size=output_size, data_format=self.data_format)
