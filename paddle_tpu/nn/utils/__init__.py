"""nn.utils (reference: python/paddle/nn/utils/ — weight_norm_hook.py,
spectral_norm_hook.py, clip_grad_value_.py, transform_parameters.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Parameter, Tensor, _unwrap
from ..clip import clip_grad_norm_  # noqa: F401

__all__ = [
    "weight_norm",
    "remove_weight_norm",
    "spectral_norm",
    "clip_grad_norm_",
    "clip_grad_value_",
    "parameters_to_vector",
    "vector_to_parameters",
]


def parameters_to_vector(parameters, name=None):
    from ...ops.manipulation import concat, reshape

    return concat([reshape(p, (-1,)) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    v = _unwrap(vec)
    for p in parameters:
        n = p.size
        p._value = jnp.reshape(v[offset : offset + n], p.shape).astype(p.dtype)
        offset += n


def clip_grad_value_(parameters, clip_value):
    """Clamp every parameter's gradient to [-clip_value, clip_value] in place
    (reference: python/paddle/nn/utils/clip_grad_value_.py)."""
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    clip_value = float(clip_value)
    for p in params:
        if p._grad is not None:
            p._grad = jnp.clip(p._grad, -clip_value, clip_value)


def _norm_except_dim(w, dim):
    """L2 norm reduced over every axis except ``dim`` (paddle's
    norm_except_dim); ``dim=None`` reduces everything to a scalar."""
    w = w.astype(jnp.float32)
    if dim is None:
        return jnp.sqrt(jnp.sum(w * w))
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes))


def _wn_broadcast(vec, ndim, dim):
    if dim is None:
        return vec
    shape = [1] * ndim
    shape[dim] = -1
    return jnp.reshape(vec, shape)


def _compute_weight_norm(g, v, dim):
    """g * v / ||v||, recorded through apply_op so eager backward reaches
    the g/v parameters (they are the only trainables after weight_norm)."""
    from ...core.tensor import apply_op

    out_dtype = _unwrap(v).dtype

    def fn(gv, vv):
        vv32 = vv.astype(jnp.float32)
        norm = _wn_broadcast(_norm_except_dim(vv32, dim), vv32.ndim, dim)
        w = _wn_broadcast(gv.astype(jnp.float32), vv32.ndim, dim) * vv32 \
            / jnp.maximum(norm, 1e-12)
        return w.astype(out_dtype)

    return apply_op("weight_norm_recompute", fn, [g, v])


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v / ||v|| (reference:
    python/paddle/nn/utils/weight_norm_hook.py).  Adds trainable
    ``<name>_g`` / ``<name>_v`` and recomputes the weight in a
    forward-pre-hook, so optimizer steps on g/v flow into the layer."""
    if hasattr(layer, f"_{name}_wn_hook"):
        raise ValueError(f"weight_norm already applied to parameter {name}")
    w = getattr(layer, name)
    wv = _unwrap(w)
    g = Parameter(_norm_except_dim(wv, dim).astype(wv.dtype), name=f"{name}_g")
    v = Parameter(wv, name=f"{name}_v")
    del layer._parameters[name]
    layer.add_parameter(f"{name}_g", g)
    layer.add_parameter(f"{name}_v", v)

    def hook(lyr, inputs):
        computed = _compute_weight_norm(
            lyr._parameters[f"{name}_g"], lyr._parameters[f"{name}_v"], dim)
        object.__setattr__(lyr, name, computed)
        return None

    hook(layer, None)  # materialize immediately so eager access works
    handle = layer.register_forward_pre_hook(hook)
    object.__setattr__(layer, f"_{name}_wn_hook", (handle, dim))
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g/v back into a single ``<name>`` parameter and drop the hook."""
    state = getattr(layer, f"_{name}_wn_hook", None)
    if state is None:
        raise ValueError(f"weight_norm not applied to parameter {name}")
    handle, dim = state
    handle.remove()
    w = _unwrap(_compute_weight_norm(layer._parameters[f"{name}_g"],
                                     layer._parameters[f"{name}_v"], dim))
    del layer._parameters[f"{name}_g"]
    del layer._parameters[f"{name}_v"]
    object.__delattr__(layer, f"_{name}_wn_hook")
    if name in layer.__dict__:
        object.__delattr__(layer, name)
    layer.add_parameter(name, Parameter(w, name=name))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=0):
    """Divide ``layer.<name>`` by its largest singular value, estimated with
    power iteration (reference: python/paddle/nn/utils/spectral_norm_hook.py).
    The u/v iteration vectors live in non-persistable buffers and advance one
    step per forward while the layer is training."""
    if hasattr(layer, f"_{name}_sn_hook"):
        raise ValueError(f"spectral_norm already applied to parameter {name}")
    w = getattr(layer, name)
    wv = _unwrap(w)
    if wv.ndim < 2:
        raise ValueError("spectral_norm expects a weight with ndim >= 2")
    import jax

    from ...core import rng
    from ...core.tensor import apply_op

    mat0 = jnp.moveaxis(wv.astype(jnp.float32), dim, 0).reshape(wv.shape[dim], -1)
    h, wdim = mat0.shape
    u0 = jax.random.normal(rng.next_key(), (h,), jnp.float32)
    v0 = jax.random.normal(rng.next_key(), (wdim,), jnp.float32)
    orig = Parameter(wv, name=f"{name}_orig")
    del layer._parameters[name]
    layer.add_parameter(f"{name}_orig", orig)
    layer.register_buffer(f"{name}_u", Tensor(u0 / jnp.linalg.norm(u0)),
                          persistable=False)
    layer.register_buffer(f"{name}_v", Tensor(v0 / jnp.linalg.norm(v0)),
                          persistable=False)

    def hook(lyr, inputs):
        # power iteration on detached values (the reference also detaches
        # u/v); only the final w/sigma division is recorded on the tape so
        # backward reaches weight_orig
        wcur = _unwrap(lyr._parameters[f"{name}_orig"])
        mat = jnp.moveaxis(wcur.astype(jnp.float32), dim, 0).reshape(wcur.shape[dim], -1)
        u = _unwrap(lyr._buffers[f"{name}_u"])
        v = _unwrap(lyr._buffers[f"{name}_v"])
        iters = n_power_iterations if getattr(lyr, "training", True) else 0
        for _ in range(max(iters, 0)):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        lyr._buffers[f"{name}_u"] = Tensor(u)
        lyr._buffers[f"{name}_v"] = Tensor(v)

        def fn(worig, uu, vv):
            m = jnp.moveaxis(worig.astype(jnp.float32), dim, 0
                             ).reshape(worig.shape[dim], -1)
            sigma = uu @ (m @ vv)
            return (worig.astype(jnp.float32)
                    / jnp.maximum(sigma, eps)).astype(worig.dtype)

        computed = apply_op("spectral_norm_recompute", fn,
                            [lyr._parameters[f"{name}_orig"],
                             Tensor(u), Tensor(v)])
        object.__setattr__(lyr, name, computed)
        return None

    hook(layer, None)
    handle = layer.register_forward_pre_hook(hook)
    object.__setattr__(layer, f"_{name}_sn_hook", (handle, dim))
    return layer


def weight_norm_except_dim(w, dim=None):  # parity helper used by some configs
    return Tensor(_norm_except_dim(_unwrap(w), dim))
