"""nn.utils (reference: python/paddle/nn/utils/)."""

from ..clip import clip_grad_norm_  # noqa: F401


def parameters_to_vector(parameters, name=None):
    from ...ops.manipulation import concat, reshape

    return concat([reshape(p, (-1,)) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    import jax.numpy as jnp

    from ...core.tensor import _unwrap

    v = _unwrap(vec)
    for p in parameters:
        n = p.size
        p._value = jnp.reshape(v[offset : offset + n], p.shape).astype(p.dtype)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    return layer  # placeholder: spectral/weight norm reparameterization


def remove_weight_norm(layer, name="weight"):
    return layer
