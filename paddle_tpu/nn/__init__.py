"""paddle_tpu.nn — layers, functional, initializers (reference: python/paddle/nn/)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from . import utils  # noqa: F401
from .activation import *  # noqa: F401,F403
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .common import *  # noqa: F401,F403
from .container import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .layer_base import Layer, ParamAttr  # noqa: F401
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
