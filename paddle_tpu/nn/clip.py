"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByGlobalNorm used by every LLM recipe; the distributed-aware variant
lives in paddle_tpu.distributed.fleet.HybridParallelClipGrad)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, _unwrap

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm", "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (param, grad Tensor) pairs → same with clipped grads."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
            else:
                out.append((p, Tensor(jnp.clip(_unwrap(g), self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            gv = _unwrap(g)
            n = jnp.sqrt(jnp.sum(gv.astype(jnp.float32) ** 2))
            factor = jnp.where(n > self.clip_norm, self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, Tensor((gv * factor).astype(gv.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        self.auto_skip_clip = auto_skip_clip

    def _global_norm_sq(self, params_grads):
        sq = 0.0
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            gv = _unwrap(g)
            sq = sq + jnp.sum(gv.astype(jnp.float32) ** 2)
        return sq

    def __call__(self, params_grads):
        sq = self._global_norm_sq(params_grads)
        if isinstance(sq, float):  # no clippable grads
            return params_grads
        gn = jnp.sqrt(sq)
        factor = jnp.where(gn > self.clip_norm, self.clip_norm / jnp.maximum(gn, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
            else:
                gv = _unwrap(g)
                out.append((p, Tensor((gv * factor.astype(jnp.float32)).astype(gv.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style utility paddle also exposes (paddle.nn.utils.clip_grad_norm_)."""
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p._grad for p in params if p._grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack([jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type) for g in grads])) ** (
            1.0 / norm_type
        )
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        if p._grad is not None:
            p._grad = (p._grad * factor).astype(p._grad.dtype)
    return Tensor(total)
