"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from . import functional as F
from . import initializer as I
from .layer_base import Layer

__all__ = [
    "ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax", "Softmax2D",
    "LogSoftmax", "LeakyReLU", "ELU", "CELU", "SELU", "SiLU", "Silu",
    "Swish", "Mish", "GLU",
    "Hardswish", "Hardsigmoid", "Hardtanh", "Hardshrink", "Softshrink",
    "Softplus", "Softsign", "Tanhshrink", "ThresholdedReLU", "LogSigmoid",
    "Maxout", "PReLU", "RReLU",
]


def _simple(name, fwd):
    cls = type(name, (Layer,), {"forward": fwd})
    globals()[name] = cls
    return cls


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self.approximate)


class Sigmoid(Layer):
    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return F.tanh(x)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554804934193349852946,
                 alpha=1.6732632423543772848170429916717, name=None):
        super().__init__()
        self._scale = scale
        self._alpha = alpha

    def forward(self, x):
        return F.selu(x, scale=self._scale, alpha=self._alpha)


class SiLU(Layer):
    def forward(self, x):
        return F.silu(x)


Silu = SiLU  # reference activation.py exports the `Silu` spelling


class Softmax2D(Layer):
    """activation.py Softmax2D: softmax over the channel axis of NCHW / CHW
    inputs (each spatial location's channel vector sums to 1)."""

    def forward(self, x):
        ndim = len(x.shape)
        if ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D expects a 3D or 4D input, got {ndim}D")
        return F.softmax(x, axis=-3)


class Swish(Layer):
    def forward(self, x):
        return F.swish(x)


class Mish(Layer):
    def forward(self, x):
        return F.mish(x)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, axis=self.axis)


class Hardswish(Layer):
    def forward(self, x):
        return F.hardswish(x)


class Hardsigmoid(Layer):
    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class Softsign(Layer):
    def forward(self, x):
        return F.softsign(x)


class Tanhshrink(Layer):
    def forward(self, x):
        return F.tanhshrink(x)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold, self.value)


class LogSigmoid(Layer):
    def forward(self, x):
        return F.log_sigmoid(x)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr, default_initializer=I.Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        if self.training:
            from ..core import rng
            import jax

            from ..core.tensor import apply_op

            key = rng.next_key()

            def fn(v):
                import jax.numpy as jnp

                a = jax.random.uniform(key, v.shape, v.dtype, self.lower, self.upper)
                return jnp.where(v >= 0, v, a * v)

            return apply_op("rrelu", fn, [x])
        return F.leaky_relu(x, (self.lower + self.upper) / 2)
