"""Graph-learning ops (reference: python/paddle/geometric/ — message passing
send_u_recv/send_ue_recv in message_passing/send_recv.py, segment math in
math.py backed by phi segment_pool kernels, sampling in sampling/).

TPU-native: all segment ops map to jax.ops.segment_* (XLA scatter-reduce —
one fused kernel, deterministic on TPU); message passing composes gather +
segment-reduce."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
]


def _num_segments(segment_ids, out_size):
    if out_size is not None:
        return int(out_size)
    ids = _unwrap(segment_ids)
    return int(jnp.max(ids)) + 1 if ids.size else 0


def segment_sum(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply_op("segment_sum",
                    lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
                    [data, segment_ids])


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def fn(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(i, d.dtype), i, num_segments=n)
        return s / jnp.maximum(cnt, 1)[(...,) + (None,) * (d.ndim - 1)]

    return apply_op("segment_mean", fn, [data, segment_ids])


def segment_max(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply_op("segment_max",
                    lambda d, i: jax.ops.segment_max(d, i, num_segments=n),
                    [data, segment_ids])


def segment_min(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply_op("segment_min",
                    lambda d, i: jax.ops.segment_min(d, i, num_segments=n),
                    [data, segment_ids])


_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled inline
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and reduce onto dst (reference
    geometric/message_passing/send_recv.py:send_u_recv)."""
    if reduce_op not in _REDUCERS:
        raise ValueError(f"unknown reduce_op {reduce_op!r}")
    n = _num_segments(dst_index, out_size) if out_size is not None else None

    def fn(xv, src, dst):
        num = n if n is not None else xv.shape[0]
        msgs = jnp.take(xv, src, axis=0)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=num)
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, xv.dtype), dst,
                                      num_segments=num)
            return s / jnp.maximum(cnt, 1)[(...,) + (None,) * (msgs.ndim - 1)]
        return _REDUCERS[reduce_op](msgs, dst, num_segments=num)

    return apply_op("send_u_recv", fn, [x, src_index, dst_index])


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Messages combine node features with edge features (reference
    send_recv.py:send_ue_recv)."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]
    n = _num_segments(dst_index, out_size) if out_size is not None else None

    def fn(xv, yv, src, dst):
        num = n if n is not None else xv.shape[0]
        msgs = combine(jnp.take(xv, src, axis=0), yv)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=num)
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, msgs.dtype), dst,
                                      num_segments=num)
            return s / jnp.maximum(cnt, 1)[(...,) + (None,) * (msgs.ndim - 1)]
        return _REDUCERS[reduce_op](msgs, dst, num_segments=num)

    return apply_op("send_ue_recv", fn, [x, y, src_index, dst_index])


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Edge-wise message from both endpoints (reference send_recv.py:send_uv)."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def fn(xv, yv, src, dst):
        return combine(jnp.take(xv, src, axis=0), jnp.take(yv, dst, axis=0))

    return apply_op("send_uv", fn, [x, y, src_index, dst_index])


def _compact_ids(xv, neighbor_arrays):
    """First-appearance id compaction shared by reindex_graph and
    reindex_heter_graph: x's nodes keep their order (0..len(x)-1), new
    neighbor ids append in first-appearance order (the reference contract:
    x=[0,1,2], neighbors=[8,9,0,4,7,6,7] -> out_nodes=[0,1,2,8,9,4,7,6])."""
    import numpy as np

    seen = set(int(v) for v in xv)
    extra = []
    for nb in neighbor_arrays:
        for v in nb:
            if int(v) not in seen:
                seen.add(int(v))
                extra.append(v)
    node_ids = np.concatenate([xv, np.asarray(extra, xv.dtype)]) \
        if extra else xv.copy()
    lookup = {int(v): i for i, v in enumerate(node_ids)}
    return node_ids, lookup


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global ids to local ids (reference
    geometric/reindex.py:reindex_graph). Host-side utility (ragged)."""
    import numpy as np

    xv = np.asarray(_unwrap(x))
    nb = np.asarray(_unwrap(neighbors))
    node_ids, lookup = _compact_ids(xv, [nb])
    reindex_src = np.fromiter((lookup[int(v)] for v in nb), np.int64, len(nb))
    cnt = np.asarray(_unwrap(count))
    reindex_dst = np.repeat(np.arange(len(cnt)), cnt)
    return Tensor(reindex_src), Tensor(reindex_dst), Tensor(node_ids)


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Multi-edge-type reindex (reference geometric/reindex.py:153): the
    neighbor/count pairs of several graphs share ONE id compaction; the
    per-graph edge lists concatenate after reindexing."""
    import numpy as np

    xv = np.asarray(_unwrap(x))
    nbs = [np.asarray(_unwrap(n)) for n in neighbors]
    cnts = [np.asarray(_unwrap(c)) for c in count]
    node_ids, lookup = _compact_ids(xv, nbs)
    srcs, dsts = [], []
    for nb, cnt in zip(nbs, cnts):
        srcs.append(np.fromiter((lookup[int(v)] for v in nb), np.int64,
                                len(nb)))
        dsts.append(np.repeat(np.arange(len(cnt)), cnt))
    return (Tensor(np.concatenate(srcs)), Tensor(np.concatenate(dsts)),
            Tensor(node_ids))


def _sample_neighbors_impl(row, colptr, input_nodes, sample_size, eids,
                           return_eids, pick_fn):
    """Shared CSC sampling machinery: per-node neighbor slice, eids
    packing, framework-Generator seeding (paddle.seed reproducible).
    ``pick_fn(rs, lo, hi)`` returns the chosen row positions for one node."""
    import numpy as np

    import jax as _jax

    from ..core import rng as _rng

    if return_eids and eids is None:
        raise ValueError("`eids` should not be None if `return_eids` is True.")
    rowv = np.asarray(_unwrap(row)).ravel()
    cp = np.asarray(_unwrap(colptr)).ravel()
    nodes = np.asarray(_unwrap(input_nodes)).ravel()
    ev = np.asarray(_unwrap(eids)).ravel() if eids is not None else None
    seed = int(_jax.random.randint(_rng.next_key(), (), 0, 2**31 - 1))
    rs = np.random.RandomState(seed)
    out_nb, out_cnt, out_eids = [], [], []
    for n in nodes:
        lo, hi = int(cp[int(n)]), int(cp[int(n) + 1])
        if sample_size < 0 or hi - lo <= sample_size:
            pick = np.arange(lo, hi)
        else:
            pick = pick_fn(rs, lo, hi)
        out_nb.append(rowv[pick])
        out_cnt.append(len(pick))
        if ev is not None:
            out_eids.append(ev[pick])
    nb = (np.concatenate(out_nb) if out_nb else np.empty((0,), rowv.dtype))
    cnt = np.asarray(out_cnt, np.int32)
    if return_eids:
        ee = (np.concatenate(out_eids) if out_eids
              else np.empty((0,), rowv.dtype))
        return Tensor(nb), Tensor(cnt), Tensor(ee)
    return Tensor(nb), Tensor(cnt)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """CSC neighbor sampling (reference geometric/sampling/neighbors.py:68):
    for each input node, draw up to ``sample_size`` of its in-neighbors
    uniformly without replacement (all of them when -1).  Host-side utility
    (ragged outputs)."""
    import numpy as np

    def pick(rs, lo, hi):
        return lo + rs.choice(hi - lo, size=sample_size, replace=False)

    return _sample_neighbors_impl(row, colptr, input_nodes, sample_size,
                                  eids, return_eids, pick)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-biased sampling without replacement (reference
    neighbors.py:256), A-Res/Gumbel top-k semantics: probability
    proportional to ``edge_weight``; zero-weight edges sort last but can
    still fill the sample when positive-weight edges run out (the
    reference's reservoir behavior — a p= multinomial would crash there)."""
    import numpy as np

    wv = np.asarray(_unwrap(edge_weight)).ravel().astype(np.float64)

    def pick(rs, lo, hi):
        w = wv[lo:hi]
        with np.errstate(divide="ignore"):
            keys = np.where(w > 0, np.log(np.maximum(w, 1e-300)), -np.inf)
        keys = keys + rs.gumbel(size=hi - lo)
        return lo + np.argsort(-keys)[:sample_size]

    return _sample_neighbors_impl(row, colptr, input_nodes, sample_size,
                                  eids, return_eids, pick)


__all__ += ["reindex_heter_graph", "sample_neighbors",
            "weighted_sample_neighbors"]
