#!/usr/bin/env python
"""CI gate: jaxpr-level TPU lint + static program-card budgets over every
registered target.

Per target the gate runs the six lint rules — including the
kernel-contract verifier (``paddle_tpu/analysis/kernel_contracts.py``:
index-map bounds, output write races, alias safety for every
``pallas_call``) — AND derives the static ProgramCard (peak live HBM,
launch census, collective bytes, VMEM fit, trace families,
kernel-contract sections — ``paddle_tpu/analysis/cost_model.py``) in one
build/trace pass; serving targets additionally run the host-contract pass
(``paddle_tpu/analysis/host_contracts.py``: ``_host_overlap()`` race /
blocking-fetch analysis + fleet/request state-machine protocol
verification, memoized module-wide), whose findings gate through the same
allowlist and whose sections ride the card; cards are then checked
against the reasoned per-target ceilings in
``paddle_tpu/analysis/budgets.toml``.  The KNOWN_KERNELS
drift lint (dead / unregistered kill switches) runs once after the target
loop, gated like stale allowlist entries.  Exits 0 when every target is
clean
(or fully allowlisted) AND within budget — wired into the tier-1 suite
(tests/test_analysis.py::test_lint_gate_over_registered_targets,
tests/test_program_cards.py::test_card_gate_over_registered_targets) so a
change that knocks a hot path off the fast path (f32 upcast, dropped
donation, cache-key churn, a stray callback) OR regresses its static cost
(a scatter back on the fused decode path, peak HBM growth, a doubled trace
family, an over-VMEM launch) fails the suite instead of surfacing as bench
drift rounds later.

Usage::

    JAX_PLATFORMS=cpu python tools/lint_gate.py [--verbose]
        [--strict-allowlist] [--cards-only] [--json]
        [--allowlist PATH] [--budgets PATH]

``--strict-allowlist`` turns stale allowlist entries (suppressions that
matched NO finding across all targets — a reviewed-and-fixed leak whose
pragma lingers) from a warning into a gate failure.  ``--cards-only``
skips the lint rules and runs just the card/budget layer.  ``--json``
replaces the text output with one machine-readable document — per-target
findings/allowlisted plus the full card summary (``kernel_contracts`` and
``host_contracts`` sections included), budget findings, drift and stale
sweeps; exit codes are unchanged.  The PATH overrides exist for tests;
CI runs the packaged files.

Exit codes: 0 clean, 1 gating findings (lint, budget, or strict-stale),
2 a target failed to build/trace (a broken target is a gate failure, not a
skip — otherwise a refactor that renames a traced function silently turns
the gate off).
"""

from __future__ import annotations

import sys
import traceback


def _parse_argv(argv):
    """Strict argparse flag parsing (no abbreviations): an unrecognized
    token — a CI job typo like ``--strict_allowlist`` — exits 2 rather
    than running the gate under the wrong configuration and reporting
    success."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python tools/lint_gate.py", allow_abbrev=False,
        description="CI gate: TPU lint + program-card budgets over every "
                    "registered analysis target")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("--strict-allowlist", action="store_true",
                   help="stale allowlist entries gate instead of warning")
    p.add_argument("--cards-only", action="store_true",
                   help="skip the lint rules; run just the card/budget gate")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable document instead of "
                        "text (exit codes unchanged)")
    p.add_argument("--allowlist", default=None, metavar="PATH")
    p.add_argument("--budgets", default=None, metavar="PATH")
    return p.parse_args(argv)


def main(argv=None) -> int:
    """Pure gate logic: assumes paddle_tpu is importable and the backend is
    already configured (the ``__main__`` block does both for script use;
    the in-process tier-1 tests run under conftest's CPU-forced config) —
    no process-global mutation here, so an in-process caller's environment
    survives the gate."""
    args = _parse_argv(sys.argv[1:] if argv is None else list(argv))
    verbose = args.verbose
    strict_allowlist = args.strict_allowlist
    cards_only = args.cards_only
    allowlist_path = args.allowlist
    budgets_path = args.budgets
    json_mode = args.json
    # --json: text output is replaced wholesale by one document printed at
    # the end; every section the text mode prints has a key here
    doc = {"targets": [], "budget_findings": [], "registry_drift": [],
           "stale_allowlist": []} if json_mode else None

    if cards_only and strict_allowlist:
        # the stale-allowlist sweep needs the lint reports the cards-only
        # path never produces — accepting the combination would be a
        # silent no-op reporting success under the wrong configuration
        print("lint gate: --strict-allowlist requires the lint pass; "
              "drop --cards-only", file=sys.stderr)
        return 2

    from paddle_tpu.analysis import load_allowlist
    from paddle_tpu.analysis.cost_model import (check_budgets, gate_cards,
                                                load_budgets)
    from paddle_tpu.analysis.targets import (GATE_TARGETS, TARGETS, run,
                                             run_card)

    # load both config files BEFORE the (minutes-long) target loop: a
    # typoed --allowlist/--budgets path or a malformed file must fail
    # immediately with the documented exit contract, not as an uncaught
    # traceback after all the work
    try:
        allowlist = load_allowlist(allowlist_path)
        budgets = load_budgets(budgets_path)
    except Exception as e:
        print(f"lint gate: cannot load allowlist/budgets: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    rc = 0
    cards = {}
    reports = []
    for name in GATE_TARGETS:
        try:
            if cards_only:
                # the cards-only path IS targets.run_card (build + env
                # pins + build_card) — one implementation, two gates
                cards[name] = run_card(name)
                if json_mode:
                    doc["targets"].append(
                        {"target": name, "card": cards[name].summary()})
                continue
            # targets.run applies the target's env pins + analyze_kwargs —
            # the single implementation every gate entry point shares
            report = run(name, card=True, allowlist=allowlist)
        except Exception:
            print(f"== {name}: FAILED to build/trace ==", file=sys.stderr)
            traceback.print_exc()
            rc = max(rc, 2)
            continue
        reports.append(report)
        if report.card is not None:
            cards[name] = report.card
        if json_mode:
            import dataclasses

            doc["targets"].append({
                "target": name, "ok": report.ok,
                "card": (report.card.summary()
                         if report.card is not None else None),
                "findings": [dataclasses.asdict(f) for f in report.findings],
                "allowlisted": [{**dataclasses.asdict(f),
                                 "reason": a.reason}
                                for f, a in report.allowlisted]})
        else:
            print(report.render(verbose=verbose))
        if not report.ok:
            rc = max(rc, 1)

    # --- program-card budget gate (cost_model.py, budgets.toml) ---------
    if cards_only:
        # the ONE cards-gate policy, shared with the --cards CLI (card
        # findings pass the allowlist exactly like the full-gate path)
        budget_findings = gate_cards(cards, budgets, allowlist=allowlist,
                                     registered=TARGETS)
    else:
        # analyze() already folded card findings into each report
        budget_findings = check_budgets(cards, budgets, registered=TARGETS)
    for f in budget_findings:
        if json_mode:
            import dataclasses

            doc["budget_findings"].append(dataclasses.asdict(f))
        else:
            print("  " + f.render()
                  + (f"  <{f.target}>" if f.target else ""))
        if f.severity != "info":
            rc = max(rc, 1)

    # --- KNOWN_KERNELS drift (dead / unregistered kill switches) --------
    # cross-references the PADDLE_TPU_DISABLE_PALLAS vocabulary against
    # the kernel_disabled() dispatch sites actually in the package
    # (analysis/kernel_contracts.py); same policy as stale allowlist
    # entries — warning by default, gating under --strict-allowlist, so a
    # renamed or retired kernel cannot leave a dead kill switch behind
    if not cards_only:
        from paddle_tpu.analysis import registry_drift_findings

        for f in registry_drift_findings():
            if json_mode:
                doc["registry_drift"].append(
                    {"rule": f.rule, "message": f.message,
                     "gating": strict_allowlist})
            elif strict_allowlist:
                print(f"  ERROR   {f.rule}: {f.message}")
            else:
                print(f"  warning {f.rule}: {f.message} "
                      f"(gating under --strict-allowlist)")
            if strict_allowlist:
                rc = max(rc, 1)

    # --- stale-allowlist detection (suppressions covering nothing) ------
    if rc >= 2:
        # a target that failed to build produced no report: its live
        # allowlist entries would be falsely reported stale with
        # "delete the entry" advice — skip the sweep; the exit code
        # already signals the broken gate
        print("  (stale-allowlist sweep skipped: a target failed to "
              "build, its suppressions cannot be attributed)")
    elif not cards_only:
        used = {id(a) for r in reports for _, a in r.allowlisted}
        stale = [a for a in allowlist if id(a) not in used]
        for a in stale:
            line = (f"allowlist entry matched no finding across all "
                    f"registered targets (rule={a.rule!r} "
                    f"target={a.target!r} match={a.match!r}) — the "
                    f"suppressed finding was fixed or renamed; delete the "
                    f"entry (reason on file: {a.reason[:80]})")
            if json_mode:
                doc["stale_allowlist"].append(
                    {"rule": a.rule, "target": a.target, "match": a.match,
                     "gating": strict_allowlist})
            elif strict_allowlist:
                print(f"  ERROR   stale_allowlist: {line}")
            else:
                print(f"  warning stale_allowlist: {line} "
                      f"(gating under --strict-allowlist)")
            if strict_allowlist:
                rc = max(rc, 1)

    if json_mode:
        import json

        doc["ok"] = rc == 0
        doc["exit"] = rc
        print(json.dumps(doc, indent=2))
    if rc == 1 and not json_mode:
        print("\nlint gate FAILED: fix the findings, allowlist them in "
              "paddle_tpu/analysis/allowlist.toml (with a reason), or — "
              "for budget regressions you mean to keep — re-run "
              "`python -m paddle_tpu.analysis --cards --update-budgets` "
              "and justify the new ceilings in budgets.toml",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    # script invocation: make the repo importable and pin the CPU backend
    # (analysis is pure tracing — never grab a TPU, never fail on a relay
    # outage).  Kept out of main() so the in-process tier-1 test does not
    # leak env/config mutations into the rest of the pytest run.
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the multi-device target (serving_tp_step) needs a host mesh: force
    # the virtual CPU device count like tests/conftest.py (pre-init only)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
    import jax

    try:
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass
    sys.exit(main())
