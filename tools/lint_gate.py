#!/usr/bin/env python
"""CI gate: run the jaxpr-level TPU lint over every registered target.

Exits 0 when every target is clean or fully allowlisted
(``paddle_tpu/analysis/allowlist.toml``), nonzero otherwise — wired into the
tier-1 suite (tests/test_analysis.py::test_lint_gate_over_registered_targets)
so a change that knocks a train step or the serving hot path off the TPU
fast path (f32 upcast, dropped donation, cache-key churn, a stray callback)
fails the suite instead of surfacing as bench drift rounds later.

Usage::

    JAX_PLATFORMS=cpu python tools/lint_gate.py [--verbose]

Exit codes: 0 clean, 1 gating findings, 2 a target failed to build/trace
(a broken target is a gate failure, not a skip — otherwise a refactor that
renames a traced function silently turns the gate off).
"""

from __future__ import annotations

import sys
import traceback


def main(argv=None) -> int:
    """Pure gate logic: assumes paddle_tpu is importable and the backend is
    already configured (the ``__main__`` block does both for script use;
    the in-process tier-1 test runs under conftest's CPU-forced config) —
    no process-global mutation here, so an in-process caller's environment
    survives the gate."""
    argv = sys.argv[1:] if argv is None else argv
    verbose = "--verbose" in argv or "-v" in argv

    from paddle_tpu.analysis.targets import GATE_TARGETS, run

    rc = 0
    for name in GATE_TARGETS:
        try:
            report = run(name)
        except Exception:
            print(f"== {name}: FAILED to build/trace ==", file=sys.stderr)
            traceback.print_exc()
            rc = max(rc, 2)
            continue
        print(report.render(verbose=verbose))
        if not report.ok:
            rc = max(rc, 1)
    if rc == 1:
        print("\nlint gate FAILED: fix the findings or allowlist them in "
              "paddle_tpu/analysis/allowlist.toml (with a reason)",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    # script invocation: make the repo importable and pin the CPU backend
    # (analysis is pure tracing — never grab a TPU, never fail on a relay
    # outage).  Kept out of main() so the in-process tier-1 test does not
    # leak env/config mutations into the rest of the pytest run.
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the multi-device target (serving_tp_step) needs a host mesh: force
    # the virtual CPU device count like tests/conftest.py (pre-init only)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
    import jax

    try:
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:
        pass
    sys.exit(main())
