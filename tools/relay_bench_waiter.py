"""Round-long relay watcher: probe the TPU relay every ~10 min; on the first
healthy window run the FULL bench sweep (`python bench.py` — the driver's
exact command), which banks every fresh TPU rung to BENCH_TPU_CACHE.json.
Keeps watching until every target rung family is banked or the deadline
passes, so a mid-round relay outage can't cost the round its hardware
evidence (the failure mode of rounds 3 and 4).

Usage: python tools/relay_bench_waiter.py [hours] [--once]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CACHE = os.path.join(REPO, "BENCH_TPU_CACHE.json")
# one banked rung key per evidence family we want this round
TARGETS = {
    "train": "llama_train_mfu_single_chip/",
    "decode": "llama_cb_decode_tokens_per_sec/",
    "moe": "moe_train_mfu_single_chip/",
    "vision": "resnet_train_images_per_sec/",
    "dit": "dit_train_images_per_sec/",
    # round-5 evidence rungs (verdict #1/#4): exact cache keys
    "moe_bigtok": "moe_train_mfu_single_chip/full_e16_bigtok",
    "moe_dense_equiv": "moe_dense_equiv_mfu/",
    "cb_paged": "llama_cb_decode_tokens_per_sec/cb_full_chunk8_paged",
    "cb_3b_int4": "llama_cb_decode_tokens_per_sec/cb_3b_chunk8_int4",
    # round-6 evidence rungs: ragged paged-attention Pallas kernel vs the
    # gather oracle, uniform and skewed-seq_lens (docs/paged_attention.md)
    "cb_paged_kernel":
        "llama_cb_decode_tokens_per_sec/cb_full_chunk8_paged_kernel",
    "cb_paged_ragged_kernel":
        "llama_cb_decode_tokens_per_sec/cb_paged_ragged_kernel",
    "cb_paged_ragged_gather":
        "llama_cb_decode_tokens_per_sec/cb_paged_ragged_gather",
    "cb_3b_paged_kernel":
        "llama_cb_decode_tokens_per_sec/cb_3b_chunk8_int4_paged_kernel",
    # round-7 evidence rungs: automatic prefix cache hot/cold A-B (16
    # requests sharing a 256-token system prompt vs disjoint prompts) and
    # the 3B int4 variant (docs/prefix_cache.md) — exact-key matching so the
    # hot rung can never satisfy the cold half of the A/B
    "cb_prefix_hot": "llama_cb_decode_tokens_per_sec/cb_prefix_hot",
    "cb_prefix_cold": "llama_cb_decode_tokens_per_sec/cb_prefix_cold",
    "cb_3b_prefix_hot_int4":
        "llama_cb_decode_tokens_per_sec/cb_3b_prefix_hot_int4",
    # round-8 evidence rungs: speculative decoding (n-gram drafting +
    # ragged multi-token verify) hot/cold, and the SAME hot workload with
    # speculation off — the matched baseline for the >=1.5x criterion
    # (docs/speculative.md); exact keys so the hot rung can never satisfy
    # its own baseline
    "cb_spec_ngram_hot": "llama_cb_decode_tokens_per_sec/cb_spec_ngram_hot",
    "cb_spec_ngram_cold": "llama_cb_decode_tokens_per_sec/cb_spec_ngram_cold",
    "cb_spec_ngram_base": "llama_cb_decode_tokens_per_sec/cb_spec_ngram_base",
    # round-9 evidence rungs: chunked prefill + unified mixed step A/B —
    # long-prompt arrivals over an active decode batch, chunked on vs off
    # (docs/chunked_prefill.md; TBT p50/p99 + TTFT + decode_stall_steps +
    # n_traces in detail); exact keys so the mixed rung can never satisfy
    # its own stall baseline
    "cb_chunked_prefill_mixed":
        "llama_cb_decode_tokens_per_sec/cb_chunked_prefill_mixed",
    "cb_chunked_prefill_off":
        "llama_cb_decode_tokens_per_sec/cb_chunked_prefill_off",
    # round-10 evidence rung: fault-tolerant serving under overload —
    # open-loop 2x-oversubscribed arrivals + injected allocator faults,
    # headline = GOODPUT tokens/s over FINISHED requests, per-status counts
    # and degradation-ladder trips in detail (docs/fault_tolerance.md)
    "cb_overload_degrade":
        "llama_cb_decode_tokens_per_sec/cb_overload_degrade",
    # round-11 evidence rungs: tensor-parallel serving over a ("tp",) mesh
    # (docs/tp_serving.md) — the SAME workload as the matched single-chip
    # rung cb_full_chunk8_paged_kernel, at tp=2 and tp=4 (per-step
    # all-reduce bytes, kernel counters and n_traces in detail); exact
    # keys so one degree can never satisfy the other's evidence
    "cb_tp2": "llama_cb_decode_tokens_per_sec/cb_tp2",
    "cb_tp4": "llama_cb_decode_tokens_per_sec/cb_tp4",
    # round-13 evidence rungs: fleet serving behind the prefix-affinity
    # router (docs/fleet_serving.md) — open-loop arrivals over 3 replicas
    # with one injected replica_crash, headline = goodput AT the TTFT/TBT
    # SLO (router failover/hedge counters in detail).  Exact keys; the
    # smoke-sized rung runs on BOTH arms (CI twin + cheap on-hardware fleet
    # sanity), so its key banks from a TPU sweep too.
    "cb_fleet_chaos": "llama_cb_decode_tokens_per_sec/cb_fleet_chaos",
    "cb_fleet_cpu_smoke":
        "llama_cb_decode_tokens_per_sec/cb_fleet_cpu_smoke",
    # round-14 evidence rungs: long-context flash-decode A/B (PR 9 /
    # ISSUE 10, docs/paged_attention.md) — decode TBT p99 (ms) on the
    # 32k-skew workload; exact keys so the flash arm can never satisfy the
    # seq arm's wait (the acceptance criterion compares the two)
    "cb_longctx_flash": "llama_cb_decode_tbt_p99_ms/cb_longctx_flash",
    "cb_longctx_seq": "llama_cb_decode_tbt_p99_ms/cb_longctx_seq",
    # round-17 evidence rungs: hierarchical KV (ISSUE 13, docs/kv_tier.md)
    # — 4x-HBM cache pressure with the host tier on vs off (TTFT +
    # prefill_hit_rate in detail; the tier arm must beat the off arm on
    # both), plus the fleet arm where ONE shared tier absorbs
    # cross-replica affinity misses (tier_cross_readmits > 0 in detail).
    # Exact keys so the tier arm can never satisfy its own baseline; the
    # smoke banks from either backend.
    "cb_hosttier_pressure":
        "llama_cb_decode_tokens_per_sec/cb_hosttier_pressure",
    "cb_hosttier_off": "llama_cb_decode_tokens_per_sec/cb_hosttier_off",
    "cb_hosttier_cpu_smoke":
        "llama_cb_decode_tokens_per_sec/cb_hosttier_cpu_smoke",
    "cb_fleet_hosttier":
        "llama_cb_decode_tokens_per_sec/cb_fleet_hosttier",
    "cb_fleet_hosttier_cpu_smoke":
        "llama_cb_decode_tokens_per_sec/cb_fleet_hosttier_cpu_smoke",
    # round-19 evidence rungs: decode megastep stage 2 (ISSUE 15,
    # docs/paged_attention.md "Megastep stage 2").  (a) quantized-pool
    # fused-append A/B on the 32k-skew workload — int8 and packed-int4
    # pools with the in-kernel requantized append on (0 scatters/step)
    # vs off (the requant-scatter path quantized serving paid before
    # stage 2); exact keys so the fused arm can never satisfy its own
    # scatter baseline.  (b) the launch-bound pair — small-batch
    # short-context dispatch-tax regime, stage-2 fused MLP (2 launches/
    # layer) vs the stage-1 arm (3); exact keys for the same reason.
    # The cpu smokes run on BOTH backends (fleet-smoke convention).
    "cb_longctx_quant_fused":
        "llama_cb_decode_tbt_p99_ms/cb_longctx_quant_fused",
    "cb_longctx_quant_scatter":
        "llama_cb_decode_tbt_p99_ms/cb_longctx_quant_scatter",
    "cb_longctx_quant_fused_int4":
        "llama_cb_decode_tbt_p99_ms/cb_longctx_quant_fused_int4",
    "cb_longctx_quant_scatter_int4":
        "llama_cb_decode_tbt_p99_ms/cb_longctx_quant_scatter_int4",
    "cb_longctx_quant_cpu_smoke":
        "llama_cb_decode_tbt_p99_ms/cb_longctx_quant_cpu_smoke",
    "cb_longctx_quant_scatter_cpu_smoke":
        "llama_cb_decode_tbt_p99_ms/cb_longctx_quant_scatter_cpu_smoke",
    "cb_launchbound": "llama_cb_decode_tbt_p99_ms/cb_launchbound",
    "cb_launchbound_stage1":
        "llama_cb_decode_tbt_p99_ms/cb_launchbound_stage1",
    "cb_launchbound_cpu_smoke":
        "llama_cb_decode_tbt_p99_ms/cb_launchbound_cpu_smoke",
    # round-20 evidence rungs: async host runtime (ISSUE 16,
    # docs/async_runtime.md).  The asynchost A/B — the open-loop fleet
    # workload with the incremental journal + pipelined stepping ON vs
    # the serial fetch-then-bookkeep loop with per-step full snapshot()
    # rebuilds — plus the chaos variant (replica_crash mid-serve,
    # failover replaying through the incremental journal).  Exact keys
    # so the async arm can never satisfy its own serial baseline; the
    # cpu smokes run BOTH arms on both backends (fleet-smoke
    # convention) because the A/B needs both sides banked to compare.
    "cb_asynchost": "llama_cb_decode_tbt_p99_ms/cb_asynchost",
    "cb_asynchost_off": "llama_cb_decode_tbt_p99_ms/cb_asynchost_off",
    "cb_fleet_asynchost":
        "llama_cb_decode_tbt_p99_ms/cb_fleet_asynchost",
    "cb_asynchost_cpu_smoke":
        "llama_cb_decode_tbt_p99_ms/cb_asynchost_cpu_smoke",
    "cb_asynchost_off_cpu_smoke":
        "llama_cb_decode_tbt_p99_ms/cb_asynchost_off_cpu_smoke",
}


def families_banked() -> dict:
    try:
        with open(CACHE) as f:
            keys = list(json.load(f).get("rungs", {}))
    except (OSError, json.JSONDecodeError):
        keys = []

    def hit(k: str, p: str) -> bool:
        # "metric/" targets are families (any rung counts); full
        # "metric/rung" targets must match EXACTLY — prefix matching would
        # let cb_full_chunk8_paged_kernel satisfy cb_full_chunk8_paged and
        # silently drop the gather half of the kernel-vs-gather A/B
        return k.startswith(p) if p.endswith("/") else k == p

    return {fam: any(hit(k, p) for k in keys) for fam, p in TARGETS.items()}


def relay_healthy(timeout: int = 150) -> bool:
    probe = [sys.executable, "-c",
             "import jax; d=jax.devices(); print(d); "
             "import jax.numpy as jnp; print(float((jnp.ones((8,8))@"
             "jnp.ones((8,8))).sum()))"]
    try:
        out = subprocess.run(probe, capture_output=True, timeout=timeout,
                             cwd=REPO)
        return b"TPU" in out.stdout and b"512" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    hours = next((float(a) for a in sys.argv[1:] if not a.startswith("-")),
                 10.0)
    once = "--once" in sys.argv
    deadline = time.time() + hours * 3600
    while time.time() < deadline:
        missing = [f for f, ok in families_banked().items() if not ok]
        if not missing:
            print("all rung families banked — done", flush=True)
            return 0
        if relay_healthy():
            print(f"relay healthy; sweeping (missing: {missing})", flush=True)
            try:
                subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                               cwd=REPO, timeout=3600)
            except subprocess.TimeoutExpired:
                print("sweep timed out (rungs banked so far are kept)",
                      flush=True)
            if once:
                return 0
        else:
            print(f"relay down; missing={missing}; retry in 600s", flush=True)
        time.sleep(600)
    print("deadline reached", flush=True)
    return 0 if not [f for f, ok in families_banked().items() if not ok] else 1


if __name__ == "__main__":
    sys.exit(main())
