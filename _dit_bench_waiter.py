"""One-shot waiter: probe the TPU relay until it answers, then run the DiT
bench rungs (the one model family with no banked TPU evidence) and the
full MoE ladder, banking every rung to BENCH_TPU_CACHE.json.  Exits after
one successful sweep or ~6 h of probing."""

import subprocess
import sys
import time

sys.argv = ["bench.py", "--worker"]

DEADLINE = time.time() + 6 * 3600
PROBE = [sys.executable, "-c", "import jax; print(jax.devices())"]

while time.time() < DEADLINE:
    try:
        out = subprocess.run(PROBE, capture_output=True, timeout=150)
        if b"TPU" in out.stdout:
            print("relay healthy", flush=True)
            break
    except subprocess.TimeoutExpired:
        pass
    print("relay down; retry in 600s", flush=True)
    time.sleep(600)
else:
    print("gave up waiting for relay", flush=True)
    sys.exit(1)

import bench  # noqa: E402
from paddle_tpu.models import dit as _dit  # noqa: E402

results = []
dit_full = _dit.DiTConfig(image_size=32, patch_size=2, hidden_size=768,
                          depth=12, num_heads=12)
for rung in [("tiny", _dit.DiTConfig.tiny(), 4, 1, 3),
             ("full", dit_full, 16, 1, 8)]:
    try:
        r = bench.run_dit_rung(*rung)
        print(r, flush=True)
        results.append(r)
    except Exception:
        import traceback
        traceback.print_exc()
        break
bench._bank_to_cache(results)
print("banked", len(results), "dit rungs", flush=True)
